"""Serving-tier tests (dfs_tpu/serve): SIEVE cache semantics under a
byte budget, single-flight coalescing + failure non-poisoning, admission
gate shedding (unit and over real HTTP), streamed downloads with
readahead byte-identical to the plain path, and delete/GC dropping
cached entries.

Cluster scaffolding reuses test_node_cluster's helpers — nodes here run
with the serving tier ENABLED (the rest of the suite runs the default
config, which is the tier-off regression guard)."""

import asyncio
import socket
import urllib.error
import urllib.request

import numpy as np
import pytest

from dfs_tpu.config import CDCParams, ClusterConfig, NodeConfig, PeerAddr, \
    ServeConfig
from dfs_tpu.node.runtime import DownloadError, StorageNodeServer
from dfs_tpu.serve.admission import AdmissionGate, ShedError
from dfs_tpu.serve.cache import ChunkCache
from dfs_tpu.serve.singleflight import SingleFlight

CDC = CDCParams(min_size=64, avg_size=256, max_size=1024)


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def make_cluster_cfg(n: int, rf: int = 2) -> ClusterConfig:
    ports = _free_ports(2 * n)
    peers = tuple(
        PeerAddr(node_id=i + 1, host="127.0.0.1",
                 port=ports[2 * i], internal_port=ports[2 * i + 1])
        for i in range(n))
    return ClusterConfig(peers=peers, replication_factor=rf)


async def start_nodes(cluster, root, serve: ServeConfig, ids=None,
                      **cfg_kw):
    nodes = {}
    cfg_kw.setdefault("cdc", CDC)
    for p in cluster.peers:
        if ids is not None and p.node_id not in ids:
            continue
        cfg = NodeConfig(node_id=p.node_id, cluster=cluster,
                         data_root=root, fragmenter="cdc", serve=serve,
                         **cfg_kw)
        node = StorageNodeServer(cfg)
        await node.start()
        nodes[p.node_id] = node
    return nodes


async def stop_nodes(nodes) -> None:
    for n in nodes.values():
        await n.stop()


# --------------------------------------------------------------------- #
# cache.py — SIEVE semantics
# --------------------------------------------------------------------- #

def test_cache_hit_miss_and_budget_eviction():
    c = ChunkCache(budget_bytes=300)
    assert c.get("a" * 64) is None           # miss
    assert c.put("a" * 64, b"x" * 100)
    assert c.get("a" * 64) == b"x" * 100     # hit
    assert c.put("b" * 64, b"y" * 100)
    assert c.put("c" * 64, b"z" * 100)       # exactly at budget
    assert c.bytes_used == 300 and len(c) == 3
    assert c.put("d" * 64, b"w" * 100)       # forces one eviction
    assert c.bytes_used == 300 and len(c) == 3
    s = c.stats()
    assert s["evictions"] == 1 and s["hits"] == 1 and s["misses"] == 1
    # an entry bigger than the whole budget is refused outright
    assert not c.put("e" * 64, b"!" * 301)
    assert len(c) == 3


def test_cache_sieve_keeps_visited_entry_over_cold_scan():
    """The SIEVE property: a HIT entry survives the eviction pass that
    removes never-touched (scan) entries inserted after it."""
    c = ChunkCache(budget_bytes=300)
    c.put("hot0" + "a" * 60, b"h" * 100)
    c.put("cold" + "b" * 60, b"c" * 100)
    assert c.get("hot0" + "a" * 60) is not None    # mark visited
    c.put("new0" + "c" * 60, b"n" * 100)           # fills budget
    c.put("new1" + "d" * 60, b"m" * 100)           # must evict ONE
    # the cold never-visited entry goes; the visited one survives
    assert c.get("hot0" + "a" * 60) is not None
    assert "cold" + "b" * 60 not in c._map


def test_cache_drop_and_clear():
    c = ChunkCache(budget_bytes=1000)
    c.put("a" * 64, b"1" * 10)
    c.put("b" * 64, b"2" * 10)
    assert c.drop("a" * 64) and not c.drop("a" * 64)
    assert c.bytes_used == 10
    c.clear()
    assert len(c) == 0 and c.bytes_used == 0
    # eviction state (the hand) survives drops without corruption
    for i in range(9):
        c.put(f"{i}" * 64, bytes([i]) * 100)
    assert c.bytes_used <= 1000


# --------------------------------------------------------------------- #
# singleflight.py — coalescing + failure propagation
# --------------------------------------------------------------------- #

def test_singleflight_collapses_concurrent_fetches():
    calls = 0

    async def run():
        sf = SingleFlight()

        async def fetch():
            nonlocal calls
            calls += 1
            await asyncio.sleep(0.02)
            return b"payload"

        outs = await asyncio.gather(
            *(sf.do("k", fetch) for _ in range(16)))
        assert all(o == b"payload" for o in outs)
        assert sf.stats()["coalesced"] == 15

    asyncio.run(run())
    assert calls == 1


def test_singleflight_failure_reaches_waiters_without_poisoning():
    calls = 0

    async def run():
        sf = SingleFlight()

        async def failing():
            nonlocal calls
            calls += 1
            await asyncio.sleep(0.02)
            raise DownloadError("origin down")

        outs = await asyncio.gather(
            *(sf.do("k", failing) for _ in range(8)),
            return_exceptions=True)
        # the ONE origin failure propagated to every concurrent caller
        assert calls == 1
        assert all(isinstance(o, DownloadError) for o in outs)

        # ...and the key is NOT poisoned: a later attempt runs fresh
        async def ok():
            nonlocal calls
            calls += 1
            return b"fine"

        assert await sf.do("k", ok) == b"fine"
        assert sf.stats()["inflight"] == 0

    asyncio.run(run())
    assert calls == 2


# --------------------------------------------------------------------- #
# admission.py — gate semantics
# --------------------------------------------------------------------- #

def test_admission_gate_sheds_beyond_queue_depth():
    async def run():
        g = AdmissionGate("download", slots=2, queue_depth=1,
                          retry_after_s=2.0)
        await g.acquire()
        await g.acquire()                     # both slots held
        waiter = asyncio.ensure_future(g.acquire())
        await asyncio.sleep(0)                # waiter is queued (depth 1)
        with pytest.raises(ShedError) as ei:
            await g.acquire()                 # queue full -> shed
        assert ei.value.retry_after_s == 2.0
        assert g.stats()["shed"] == 1
        # the windowed gauge the doctor's shed_storm rule reads: fresh
        # sheds are in-window (it decays to 0 after SHED_WINDOW_S)
        assert g.stats()["shedRecent"] == 1
        g.release()                           # slot transfers to waiter
        await waiter
        assert g.stats()["active"] == 2
        g.release()
        g.release()
        assert g.stats()["active"] == 0

    asyncio.run(run())


def test_admission_gate_disabled_is_noop():
    async def run():
        g = AdmissionGate("upload", slots=0, queue_depth=0)
        for _ in range(100):
            await g.acquire()                 # never sheds, never counts
        assert g.stats()["active"] == 0

    asyncio.run(run())


def test_admission_cancelled_waiter_does_not_leak_slot():
    async def run():
        g = AdmissionGate("x", slots=1, queue_depth=4)
        await g.acquire()
        w1 = asyncio.ensure_future(g.acquire())
        w2 = asyncio.ensure_future(g.acquire())
        await asyncio.sleep(0)
        w1.cancel()
        await asyncio.gather(w1, return_exceptions=True)
        g.release()                           # must skip the dead waiter
        await asyncio.wait_for(w2, 1.0)
        g.release()
        assert g.stats()["active"] == 0

    asyncio.run(run())


# --------------------------------------------------------------------- #
# integration: serving tier on a real cluster
# --------------------------------------------------------------------- #

SERVE_ON = ServeConfig(cache_bytes=32 * 1024 * 1024, readahead_batches=2)


def test_concurrent_hot_reads_coalesce_to_one_origin_read(tmp_path, rng):
    """N concurrent readers of the same cold file trigger exactly ONE
    local-store read per unique chunk (single-flight), and a repeat read
    is served fully from cache (zero store reads)."""
    data = rng.integers(0, 256, size=200_000, dtype=np.uint8).tobytes()

    async def run():
        cluster = make_cluster_cfg(1, rf=1)
        nodes = await start_nodes(cluster, tmp_path, SERVE_ON)
        try:
            m, _ = await nodes[1].upload(data, "hot.bin")
            store = nodes[1].store.chunks
            reads = 0
            orig_get = store.get

            def counting_get(d):
                nonlocal reads
                reads += 1
                return orig_get(d)

            store.get = counting_get

            async def read() -> bytes:
                _, gen = await nodes[1].download_stream(m.file_id)
                return b"".join([p async for p in gen])

            outs = await asyncio.gather(*(read() for _ in range(32)))
            assert all(o == data for o in outs)
            unique = len({c.digest for c in m.chunks})
            assert reads == unique, \
                f"{reads} origin reads for {unique} unique chunks"
            # repeat read: all cache hits, zero store reads
            reads = 0
            assert await read() == data
            assert reads == 0
            assert nodes[1].serve.cache.stats()["hits"] > 0
        finally:
            await stop_nodes(nodes)

    asyncio.run(run())


def test_streamed_download_with_readahead_byte_identical(tmp_path, rng):
    """Readahead (K=2) over many small fetch batches must produce the
    exact bytes of the non-prefetching path, cross-node."""
    data = rng.integers(0, 256, size=300_000, dtype=np.uint8).tobytes()

    async def run():
        cluster = make_cluster_cfg(3)
        nodes = await start_nodes(cluster, tmp_path, SERVE_ON)
        try:
            m, _ = await nodes[1].upload(data, "ra.bin")
            nodes[2]._FETCH_BATCH_BYTES = 16 * 1024  # many batches
            _, gen = await nodes[2].download_stream(m.file_id)
            got = b"".join([p async for p in gen])
            assert got == data
            assert nodes[2].counters.snapshot()["downloads"] == 1
        finally:
            await stop_nodes(nodes)

    asyncio.run(run())


def test_failed_origin_fetch_does_not_poison_retry(tmp_path, rng):
    """Every replica of one chunk is corrupted -> concurrent reads fail;
    after the bytes are restored, the SAME node serves the file — the
    single-flight failure must not stick to the digest."""
    data = rng.integers(0, 256, size=60_000, dtype=np.uint8).tobytes()

    async def run():
        cluster = make_cluster_cfg(1, rf=1)
        nodes = await start_nodes(cluster, tmp_path, SERVE_ON)
        try:
            m, _ = await nodes[1].upload(data, "flaky.bin")
            victim = m.chunks[0].digest
            p = nodes[1].store.chunks._path(victim)
            raw = p.read_bytes()
            bad = bytes([raw[0] ^ 0xFF]) + raw[1:]
            p.write_bytes(bad)

            async def read() -> bytes:
                _, gen = await nodes[1].download_stream(m.file_id)
                return b"".join([p async for p in gen])

            outs = await asyncio.gather(*(read() for _ in range(4)),
                                        return_exceptions=True)
            assert all(isinstance(o, Exception) for o in outs)
            # restore the chunk; the next read must succeed
            nodes[1].store.chunks.put(victim, raw, verify=False)
            assert await read() == data
        finally:
            await stop_nodes(nodes)

    asyncio.run(run())


def test_waiter_survives_cancelled_leader(tmp_path, rng):
    """A reader whose single-flight leader gets CANCELLED (that client
    hung up) must re-fetch and succeed — an innocent concurrent reader
    never fails on a healthy cluster because of someone else's
    disconnect."""
    data = rng.integers(0, 256, size=60_000, dtype=np.uint8).tobytes()

    async def run():
        cluster = make_cluster_cfg(1, rf=1)
        nodes = await start_nodes(cluster, tmp_path, SERVE_ON)
        try:
            m, _ = await nodes[1].upload(data, "x.bin")
            orig = nodes[1]._fetch_verified_direct

            async def slow(*a, **kw):
                await asyncio.sleep(0.1)   # window to cancel the leader
                return await orig(*a, **kw)

            nodes[1]._fetch_verified_direct = slow

            async def read() -> bytes:
                _, gen = await nodes[1].download_stream(m.file_id)
                return b"".join([p async for p in gen])

            leader = asyncio.ensure_future(read())
            await asyncio.sleep(0.02)      # leader holds the claims
            waiter = asyncio.ensure_future(read())
            await asyncio.sleep(0.02)      # waiter joined the flights
            leader.cancel()
            await asyncio.gather(leader, return_exceptions=True)
            assert await waiter == data
        finally:
            await stop_nodes(nodes)

    asyncio.run(run())


def test_delete_drops_cached_entries(tmp_path, rng):
    """Delete must empty the serving cache on every node — including
    entries a node only ever held as REMOTE fetches (absent from its
    local store, so the local GC dead-list alone cannot name them)."""
    data = rng.integers(0, 256, size=80_000, dtype=np.uint8).tobytes()

    async def run():
        cluster = make_cluster_cfg(2, rf=1)   # rf=1: most chunks live on
        nodes = await start_nodes(cluster, tmp_path, SERVE_ON)  # ONE node
        try:
            m, _ = await nodes[1].upload(data, "temp.bin")
            for n in nodes.values():
                _, gen = await n.download_stream(m.file_id)
                assert b"".join([p async for p in gen]) == data
                assert len(n.serve.cache) > 0
            # node 2's cache now holds chunks fetched from node 1's store
            assert await nodes[1].delete(m.file_id)
            for n in nodes.values():
                cache = n.serve.cache
                assert len(cache) == 0 and cache.bytes_used == 0, \
                    f"node {n.cfg.node_id} cache not emptied"
        finally:
            await stop_nodes(nodes)

    asyncio.run(run())


def test_http_download_sheds_503_when_gate_full(tmp_path, rng):
    """With the download gate saturated (slots held, queue_depth=0), a
    real HTTP GET /download answers 503 + Retry-After; after release it
    serves 200 with correct bytes. /metrics reports the shed."""
    data = rng.integers(0, 256, size=30_000, dtype=np.uint8).tobytes()
    serve = ServeConfig(download_slots=1, queue_depth=0,
                        retry_after_s=3.0)

    async def run():
        cluster = make_cluster_cfg(1, rf=1)
        nodes = await start_nodes(cluster, tmp_path, serve)
        port = cluster.peer(1).port
        try:
            m, _ = await nodes[1].upload(data, "shed.bin")
            url = f"http://127.0.0.1:{port}/download?fileId={m.file_id}"
            # hold the single slot directly (deterministic saturation)
            await nodes[1].serve.admission.download.acquire()

            def get():
                try:
                    with urllib.request.urlopen(url, timeout=10) as r:
                        return r.status, dict(r.headers), r.read()
                except urllib.error.HTTPError as e:
                    return e.code, dict(e.headers), e.read()

            status, headers, _ = await asyncio.to_thread(get)
            assert status == 503
            assert headers.get("Retry-After") == "3"
            nodes[1].serve.admission.download.release()
            status, _, body = await asyncio.to_thread(get)
            assert status == 200 and body == data
            # the shed is visible in /metrics
            murl = f"http://127.0.0.1:{port}/metrics"
            import json as _json

            def metrics():
                with urllib.request.urlopen(murl, timeout=10) as r:
                    return _json.loads(r.read())

            snap = await asyncio.to_thread(metrics)
            assert snap["http_shed"] == 1
            assert snap["serve"]["admission"]["download"]["shed"] == 1
        finally:
            await stop_nodes(nodes)

    asyncio.run(run())


def test_http_upload_sheds_503_when_gate_full(tmp_path, rng):
    data = rng.integers(0, 256, size=10_000, dtype=np.uint8).tobytes()
    serve = ServeConfig(upload_slots=1, queue_depth=0, retry_after_s=1.0)

    async def run():
        cluster = make_cluster_cfg(1, rf=1)
        nodes = await start_nodes(cluster, tmp_path, serve)
        port = cluster.peer(1).port
        try:
            await nodes[1].serve.admission.upload.acquire()
            url = f"http://127.0.0.1:{port}/upload?name=x.bin"

            def post():
                req = urllib.request.Request(url, data=data, method="POST")
                try:
                    with urllib.request.urlopen(req, timeout=10) as r:
                        return r.status
                except urllib.error.HTTPError as e:
                    return e.code

            assert await asyncio.to_thread(post) == 503
            nodes[1].serve.admission.upload.release()
            assert await asyncio.to_thread(post) == 201
        finally:
            await stop_nodes(nodes)

    asyncio.run(run())


def test_default_config_serving_tier_fully_off(tmp_path, rng):
    """The regression contract: a default-config node has no cache, no
    gates, and identical read results — and its /metrics shows the tier
    disabled."""
    data = rng.integers(0, 256, size=50_000, dtype=np.uint8).tobytes()

    async def run():
        cluster = make_cluster_cfg(1, rf=1)
        nodes = await start_nodes(cluster, tmp_path, ServeConfig())
        try:
            n = nodes[1]
            assert n.serve.cache is None
            assert not n.serve.read_path_enabled
            assert not n.serve.admission.download.enabled
            m, _ = await n.upload(data, "plain.bin")
            _, got = await n.download(m.file_id)
            assert got == data
            assert n.serve.flight.stats()["leads"] == 0  # never engaged
            assert n.serve.stats()["cache"] == {"enabled": False}
        finally:
            await stop_nodes(nodes)

    asyncio.run(run())
