"""Write-path pipeline benchmark -> INGEST_r07.json: windowed streaming
ingest (docs/ingest.md) vs the historical serial schedule, under
injected peer latency.

The serial write path awaited every ~flush_bytes placement batch inline,
so while a batch replicated over the network the fragmenter exhausted
its credits and the socket read stalled — replication latency was paid
in full, once per batch. The pipelined path keeps ``ingest.window``
batches in flight and ``ingest.slice_inflight`` replication slices in
flight per peer, so chunking batch N+1, local CAS writes, and peer
replication of batch N all overlap.

Method: a 3-node in-process cluster (CPU CDC engine — no device in the
loop); the two replica peers get latency injected into their
storage-plane handlers (``store_chunks`` / ``has_chunks`` sleep before
dispatch — per-request, concurrent requests overlap, exactly like real
network/disk latency). Each phase uploads fresh random data through
``upload_stream`` on a fresh cluster:

1. serial   — IngestConfig(window=1, slice_inflight=1)
2. windowed — IngestConfig(window=3, slice_inflight=2)
3. byte-identity — the windowed upload streams back down byte-identical
4. overlap evidence — /metrics ingest peaks show the window and the
   per-peer slice pipeline actually filled (>= 2 in flight)

Acceptance (full mode): windowed >= 1.5x serial throughput, byte
identity, overlap peaks > 1. ``--tiny`` is the tier-1 smoke mode
(seconds, not minutes): same phases and artifact schema, overlap +
identity gated, the speedup reported but not gated (CI hosts stall
unpredictably; the committed INGEST_r07.json carries the perf claim).

Usage: python bench_ingest_pipeline.py [--tiny] [--out PATH]
Full mode writes INGEST_r07.json (and prints it); --out overrides the
artifact path (tiny mode only writes when --out is given).
"""

from __future__ import annotations

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")  # before any dfs_tpu import

import argparse          # noqa: E402
import asyncio           # noqa: E402
import json              # noqa: E402
import socket            # noqa: E402
import sys               # noqa: E402
import time              # noqa: E402
from pathlib import Path  # noqa: E402

import numpy as np       # noqa: E402

from dfs_tpu.config import (CDCParams, ClusterConfig, IngestConfig,  # noqa: E402
                            NodeConfig, PeerAddr)
from dfs_tpu.node.runtime import StorageNodeServer  # noqa: E402

ART = "INGEST_r07.json"

# latency sized so the injected replication RTTs dominate the (GIL-
# shared, in-process) CPU work — the regime the pipeline exists for:
# the paper's north-star ingest is network/peer-bound, not chunk-bound
FULL = dict(total=48 * 2**20, block=1 << 20, flush=8 * 2**20,
            slice_bytes=4 * 2**20, store_lat=0.8, probe_lat=0.15,
            cdc=CDCParams(min_size=4096, avg_size=16384, max_size=131072))
TINY = dict(total=2 * 2**20, block=128 * 1024, flush=256 * 1024,
            slice_bytes=64 * 1024, store_lat=0.1, probe_lat=0.02,
            cdc=CDCParams(min_size=1024, avg_size=4096, max_size=16384))

SERIAL = IngestConfig(window=1, slice_inflight=1)
WINDOWED = IngestConfig(window=3, slice_inflight=2)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _inject_latency(node: StorageNodeServer, store_s: float,
                    probe_s: float) -> None:
    """Delay a peer's storage-plane ops BEFORE dispatch — per request,
    so concurrent requests overlap their delays exactly like wire/disk
    latency would."""
    orig = node._dispatch

    async def delayed(header: dict, body: bytes):
        op = header.get("op")
        if op == "store_chunks":
            await asyncio.sleep(store_s)
        elif op == "has_chunks":
            await asyncio.sleep(probe_s)
        return await orig(header, body)

    node._dispatch = delayed


async def _start_cluster(root: Path, p: dict, ingest: IngestConfig
                         ) -> dict[int, StorageNodeServer]:
    ports = _free_ports(6)
    cluster = ClusterConfig(
        peers=tuple(PeerAddr(node_id=i + 1, host="127.0.0.1",
                             port=ports[2 * i],
                             internal_port=ports[2 * i + 1])
                    for i in range(3)),
        replication_factor=2)
    nodes: dict[int, StorageNodeServer] = {}
    for i in (1, 2, 3):
        cfg = NodeConfig(node_id=i, cluster=cluster, data_root=root,
                         fragmenter="cdc", cdc=p["cdc"],
                         health_probe_s=0, ingest=ingest)
        node = StorageNodeServer(cfg)
        node._REPLICA_SLICE_BYTES = p["slice_bytes"]
        await node.start()
        nodes[i] = node
    for i in (2, 3):   # the uploader's replica peers are the slow ones
        _inject_latency(nodes[i], p["store_lat"], p["probe_lat"])
    return nodes


async def _upload_phase(root: Path, p: dict, ingest: IngestConfig,
                        data: bytes, label: str) -> dict:
    nodes = await _start_cluster(root, p, ingest)
    try:
        async def blocks():
            for off in range(0, len(data), p["block"]):
                yield data[off:off + p["block"]]

        t0 = time.perf_counter()
        manifest, stats = await nodes[1].upload_stream(blocks(), label)
        dt = time.perf_counter() - t0
        ing = nodes[1].ingest_stats()
        out = {"seconds": round(dt, 4),
               "mibps": round(len(data) / dt / 2**20, 3),
               "chunks": manifest.total_chunks,
               "transferredBytes": stats["transferredBytes"],
               "minCopies": stats["minCopies"],
               "ingest": ing}
        # byte-identity: stream the file back down from the uploader
        _, gen = await nodes[1].download_stream(manifest.file_id)
        got = b"".join([part async for part in gen])
        out["byte_identical"] = got == data
        return out
    finally:
        for n in nodes.values():
            await n.stop()


async def run_phases(p: dict, tmp: Path, tiny: bool) -> dict:
    rng = np.random.default_rng(7)
    total = p["total"]
    out: dict = {
        "metric": "ingest_pipeline", "round": 7,
        "mode": "tiny" if tiny else "full",
        "workload": {
            "total_bytes": total, "block_bytes": p["block"],
            "flush_bytes": p["flush"], "slice_bytes": p["slice_bytes"],
            "nodes": 3, "rf": 2,
            "cdc": {"min": p["cdc"].min_size, "avg": p["cdc"].avg_size,
                    "max": p["cdc"].max_size},
            "injected": {"store_chunks_s": p["store_lat"],
                         "has_chunks_s": p["probe_lat"]}},
        "serial_config": {"window": 1, "slice_inflight": 1},
        "windowed_config": {"window": WINDOWED.window,
                            "slice_inflight": WINDOWED.slice_inflight}}

    def fresh_ingest(base: IngestConfig) -> IngestConfig:
        import dataclasses
        return dataclasses.replace(base, flush_bytes=p["flush"])

    # fresh random payload per phase: cross-phase dedup would let the
    # second upload skip every transfer and void the comparison
    data_a = rng.integers(0, 256, size=total, dtype=np.uint8).tobytes()
    data_b = rng.integers(0, 256, size=total, dtype=np.uint8).tobytes()

    log("phase 1: serial ingest (window=1, slice_inflight=1)…")
    out["serial"] = await _upload_phase(
        tmp / "serial", p, fresh_ingest(SERIAL), data_a, "serial.bin")
    log(f"phase 1: {out['serial']['seconds']} s "
        f"({out['serial']['mibps']} MiB/s)")

    log(f"phase 2: windowed ingest (window={WINDOWED.window}, "
        f"slice_inflight={WINDOWED.slice_inflight})…")
    out["windowed"] = await _upload_phase(
        tmp / "windowed", p, fresh_ingest(WINDOWED), data_b,
        "windowed.bin")
    log(f"phase 2: {out['windowed']['seconds']} s "
        f"({out['windowed']['mibps']} MiB/s)")

    out["speedup"] = round(out["serial"]["seconds"]
                           / out["windowed"]["seconds"], 3)
    out["byte_identical"] = (out["serial"].pop("byte_identical")
                             and out["windowed"].pop("byte_identical"))
    stalls = out["windowed"]["ingest"]["stalls"]
    out["overlap"] = {
        "place_window_peak": stalls.get("placeWindowPeak", 0),
        "slice_inflight_peak": stalls.get("sliceInflightPeak", 0)}
    log(f"speedup {out['speedup']}x, byte_identical="
        f"{out['byte_identical']}, overlap={out['overlap']}")

    overlapped = (out["overlap"]["place_window_peak"] >= 2
                  and out["overlap"]["slice_inflight_peak"] >= 2)
    if tiny:
        # perf is NOT gated in the smoke mode — CI hosts stall
        # unpredictably; the committed full-mode artifact carries the
        # >= 1.5x claim. The smoke gates prove the overlap machinery
        # engaged and the bytes survived it.
        out["ok"] = bool(out["byte_identical"] and overlapped)
    else:
        out["ok"] = bool(out["byte_identical"] and overlapped
                         and out["speedup"] >= 1.5)
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tiny", action="store_true",
                    help="tier-1 smoke mode: seconds, overlap+identity "
                         "gated, perf reported but not gated")
    ap.add_argument("--out", default=None,
                    help="artifact path (default: INGEST_r07.json in "
                         "full mode; tiny mode writes only when given)")
    args = ap.parse_args(argv)
    p = TINY if args.tiny else FULL

    import tempfile

    # node data roots on tmpfs when available: the benchmark isolates
    # the pipeline's replication-latency hiding, and a slow container
    # filesystem (9p/overlay metadata costs ~ms per chunk file) would
    # otherwise swamp the injected peer latency with unrelated disk cost
    base = "/dev/shm" if os.path.isdir("/dev/shm") \
        and os.access("/dev/shm", os.W_OK) else None
    with tempfile.TemporaryDirectory(prefix="bench_ingest_",
                                     dir=base) as tmp:
        out = asyncio.run(run_phases(p, Path(tmp), args.tiny))
    path = args.out or (None if args.tiny
                        else Path(__file__).parent / ART)
    if path:
        Path(path).write_text(json.dumps(out, indent=2) + "\n")
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
