"""Overload-survival acceptance bench -> OVERLOAD_r18.json: the node
survives saturation, compound faults, and a slow replica
(dfs_tpu/serve deadlines+hedging, scripts/chaos_harness.py ProcLoadGen,
docs/serve.md, docs/chaos.md).

Five scripted scenarios, every one against REAL processes:

1. overload     — a 3-process cluster with admission gates ARMED and a
                  default end-to-end deadline, driven at ~5x its
                  measured capacity by the multi-process OPEN-LOOP
                  generator (offered rate never throttles on
                  completions). Gates: the shed curve engages (503s
                  with Retry-After), goodput for ADMITTED requests
                  stays within the SLO, zero acked-write loss +
                  byte-identical reads for every admitted write, the
                  post-storm census converges clean, and a
                  deadline-expired request is PROVABLY never executed
                  server-side (counter-gated: 503 + deadlineShed
                  advances + the downloads counter does not).
2. compound     — partition + disk pressure + SIGKILL in ONE run:
                  node 1 loses its link to node 2, node 3's CAS
                  answers ENOSPC, node 2 is kill -9'd mid-load, then
                  everything heals. Whatever acked survives; census
                  converges clean.
3. ring_partition — a MEMBERSHIP change during a partition (4-process
                  hash-ring cluster): node 1 is one-way partitioned
                  from node 3 while `ring add` brings standby node 4
                  in; the epoch gossips around the cut, load keeps
                  acking, and after heal the cluster converges to the
                  new epoch with a clean census.
4. ec_faults    — EC-striped corpus (k=2) on the 4-member ring; a
                  shard holder is kill -9'd mid-read and every EC file
                  must keep reading back byte-identical THROUGH the
                  outage (parity decode under load, ec_decodes > 0);
                  restart + repair converge the census clean.
5. hedged_reads — one replica made intermittently 250 ms-slow (1.2 s
                  pulses, ~1/3 duty — the GC-pause shape hedging
                  exists for); the SAME fixed read schedule runs with
                  hedging off then on. Gates: hedging cuts read p99
                  >= 2x while total issued fetch RPCs stay <= 1.2x the
                  hedging-off run (budgeted hedges never double load),
                  and hedge_fired/hedge_won counters moved.

Usage: python bench_overload.py [--tiny] [--out PATH]
Writes OVERLOAD_r18.json (or --out) and prints it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))

from scripts.chaos_harness import (ClusterHarness, LoadGen,  # noqa: E402
                                   ProcLoadGen, _sha256_hex, percentile)

ART = "OVERLOAD_r18.json"


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _counter(h: ClusterHarness, node: int, key: str) -> int:
    try:
        return int(h.metrics(node).get(key, 0) or 0)
    except Exception:  # noqa: BLE001 — dead node mid-scenario
        return 0


def _shed_total(h: ClusterHarness) -> int:
    return sum(_counter(h, i, "http_shed") for i in range(1, h.n + 1))


def _gate_stats(h: ClusterHarness, node: int, cls: str) -> dict:
    adm = (h.metrics(node).get("serve") or {}).get("admission") or {}
    return adm.get(cls) or {}


def _fetch_rpc_count(h: ClusterHarness, node: int) -> int:
    """Issued chunk-fetch RPCs from one node's client table
    (get_chunk + get_chunks, every peer, retries included)."""
    rc = (h.metrics(node).get("obs") or {}).get("rpcClient") or {}
    total = 0
    for key, row in rc.items():
        if key.endswith(":get_chunk") or key.endswith(":get_chunks"):
            total += row.get("count", 0)
    return total


def _census_gate(rep: dict, require_no_orphans: bool) -> dict:
    out = {"under_replicated": rep.get("underReplicatedTotal", -1),
           "over_replicated": rep.get("overReplicatedTotal", -1),
           "orphaned": rep.get("orphanedTotal", -1),
           "peers_failed": rep.get("peersFailed", -1)}
    out["census_clean"] = (out["under_replicated"] == 0
                          and out["over_replicated"] == 0
                          and out["peers_failed"] == 0
                          and (not require_no_orphans
                               or out["orphaned"] == 0))
    return out


# ------------------------------------------------------------------ #
# scenario 1: genuine overload against armed gates
# ------------------------------------------------------------------ #

def _measure_capacity(h: ClusterHarness, p: dict) -> float:
    """CLOSED-loop capacity probe: N threads upload back-to-back for
    the warm window — a closed loop saturates naturally (each thread
    issues the next op the moment the previous completes), so
    completions/second IS the gated cluster's capacity. An open-loop
    warm phase at a guessed rate cannot measure this: offered below
    capacity just measures the offer (observed live in r18 bring-up —
    a 12/s warm 'measured' 12/s on a cluster that could do 6x that,
    and the '5x overload' never overloaded anything)."""
    done = 0
    lock = threading.Lock()
    stop = time.time() + p["warm_s"]

    def worker(w: int) -> None:
        nonlocal done
        seq = 0
        while time.time() < stop:
            seq += 1
            try:
                status, _ = h.http(
                    1 + (w % h.n), "POST",
                    f"/upload?name=cap{w}_{seq}.bin",
                    body=os.urandom(p["payload"]),
                    timeout=p["op_timeout"])
            except OSError:
                continue
            if status == 201:
                with lock:
                    done += 1

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(p["capacity_threads"])]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=p["op_timeout"])
    return max(4.0, done / p["warm_s"])


def scenario_overload(h: ClusterHarness, p: dict) -> dict:
    capacity = _measure_capacity(h, p)
    offered = 5.0 * capacity
    shed0 = _shed_total(h)

    gen = ProcLoadGen(h, p["payload"], rate_per_s=offered,
                      procs=p["procs"], seed=22,
                      op_timeout_s=p["op_timeout"],
                      deadline_s=p["deadline_s"], retry_503=1,
                      max_inflight=p["max_inflight"],
                      workdir=h.workdir / "overload")
    # Retry-After probe: while the storm runs, a side thread hammers
    # until it catches a 503 and keeps its headers — proving the shed
    # path advertises a backoff budget, not just a bare error
    probe: dict = {}

    def probe_503() -> None:
        deadline_t = time.time() + p["overload_s"] + p["drain_s"]
        seq = 0
        while time.time() < deadline_t and "retry_after" not in probe:
            seq += 1
            try:
                status, _, hdrs = h.http_h(
                    1, "POST", f"/upload?name=probe{seq}.bin",
                    body=os.urandom(p["payload"]), timeout=30)
            except OSError:
                continue
            if status == 503:
                probe["retry_after"] = hdrs.get("retry-after")
            time.sleep(0.1)

    pt = threading.Thread(target=probe_503, daemon=True)
    pt.start()
    gen.run_for(p["overload_s"], drain_s=p["drain_s"])
    pt.join(timeout=10)

    sheds = _shed_total(h) - shed0
    s = gen.stats
    up = gen.latency_percentiles("upload")
    down = gen.latency_percentiles("download")
    goodput_p95 = max(up["p95"], down["p95"])

    # the storm is over: let repair/GC converge, then the invariant —
    # every admitted (201-acked) write reads back byte-identical
    rep = h.wait_census_clean(1, timeout=p["converge_s"],
                              require_no_orphans=False)
    verify = gen.verify_all()

    # deadline proof on the now-quiet cluster (counter-gated): a
    # request arriving with an EXPIRED budget must be 503-shed at the
    # gate — deadlineShed advances, the downloads counter does not
    # (the request provably never reached the read path)
    fid = gen.ledger[0]["fileId"] if gen.ledger else None
    dl_before = _counter(h, 1, "downloads")
    ds_before = _gate_stats(h, 1, "download").get("deadlineShed", 0)
    expired_status = None
    if fid is not None:
        expired_status, _, _ = h.http_h(
            1, "GET", f"/download?fileId={fid}",
            headers={"X-Dfs-Deadline": "0.000001"}, timeout=30)
    dl_after = _counter(h, 1, "downloads")
    ds_after = _gate_stats(h, 1, "download").get("deadlineShed", 0)

    out = {
        "capacity_ops_per_s": round(capacity, 1),
        "offered_ops_per_s": round(offered, 1),
        "offered_x_capacity": 5.0,
        "inflight_peak": s.get("inflight_peak", 0),
        "acked": s["acked"],
        "uploads_attempted": s["uploads_attempted"],
        "downloads_ok": s["downloads_ok"],
        "retries_503": s["retries_503"],
        "status_counts": s["status"],
        "sheds_503": sheds,
        "shed_curve_engaged": sheds > 0,
        "retry_after_header": probe.get("retry_after"),
        "retry_after_present": bool(probe.get("retry_after")),
        "deadline_shed_total": sum(
            _gate_stats(h, i, c).get("deadlineShed", 0)
            for i in range(1, h.n + 1)
            for c in ("download", "upload", "internal")),
        "goodput_upload": up, "goodput_download": down,
        "goodput_p95_s": goodput_p95,
        "slo_p95_s": p["slo_p95_s"],
        "goodput_within_slo": 0 < goodput_p95 <= p["slo_p95_s"],
        "verified": verify["ok"], "lost": verify["lost"],
        "zero_acked_loss": not verify["lost"],
        "byte_identical": (s["ack_hash_mismatch"] == 0
                           and s["download_mismatch"] == 0),
        "expired_deadline_status": expired_status,
        "expired_deadline_shed": ds_after - ds_before,
        "expired_deadline_downloads_ran": dl_after - dl_before,
        "deadline_never_executed": (expired_status == 503
                                    and ds_after - ds_before >= 1
                                    and dl_after == dl_before),
    }
    out.update(_census_gate(rep, require_no_orphans=False))
    out["ok"] = bool(out["shed_curve_engaged"]
                     and out["retry_after_present"]
                     and out["goodput_within_slo"]
                     and out["zero_acked_loss"]
                     and out["byte_identical"]
                     and out["deadline_never_executed"]
                     and out["census_clean"]
                     and s["acked"] > 0)
    return out


# ------------------------------------------------------------------ #
# scenario 2: compound faults — partition + disk pressure + SIGKILL
# ------------------------------------------------------------------ #

def scenario_compound(h: ClusterHarness, p: dict) -> dict:
    load = LoadGen(h, p["payload"], rate_per_s=p["rate"], seed=33,
                   upload_nodes=[1, 2], download_nodes=[1, 2],
                   op_timeout_s=p["op_timeout"])
    load.run_for(p["warm_s"])                       # healthy baseline
    # fault 1+2 together: node 1 loses its link TO node 2 (one-way)
    # while node 3's disk goes hard-full — uploads at node 2 keep
    # acking (2 reaches both), node 3 answers 507, node 1 rides handoff
    h.set_chaos(1, partition="2")
    h.set_chaos(3, disk_full=True)
    st507, _ = h.http(3, "POST", "/upload?name=full.bin",
                      body=os.urandom(p["payload"]),
                      timeout=p["op_timeout"])
    fault_thread = threading.Thread(
        target=load.run_for, args=(p["fault_s"],), daemon=True)
    fault_thread.start()
    time.sleep(max(1.0, p["fault_s"] / 3))
    # fault 3: SIGKILL node 2 while the partition + disk pressure hold
    h.kill9(2)
    time.sleep(max(1.0, p["fault_s"] / 3))
    doctor = h.doctor(1)
    saw_dead = any(f.get("rule") == "dead_peer"
                   and 2 in (f.get("peers") or [])
                   for f in doctor.get("findings", [])) \
        or doctor.get("peersFailed", 0) >= 1
    fault_thread.join()
    # heal everything: restart the corpse, clear the cut and the disk
    h.restart(2)
    h.set_chaos(1, partition="")
    h.set_chaos(3, disk_full=False)
    load.drain()
    rep = h.wait_census_clean(1, timeout=p["converge_s"],
                              require_no_orphans=False)
    verify = load.verify_all()
    s = load.snapshot()
    out = {
        "acked": s["acked"],
        "uploads_attempted": s["uploads_attempted"],
        "uploads_failed": s["uploads_failed"],
        "status_counts": s["status"],
        "full_node_upload_status": st507,
        "full_node_answers_507": st507 == 507,
        "doctor_saw_dead_peer": saw_dead,
        "verified": verify["ok"], "lost": verify["lost"],
        "zero_acked_loss": not verify["lost"],
        "byte_identical": (s["ack_hash_mismatch"] == 0
                           and s["download_mismatch"] == 0),
    }
    out.update(_census_gate(rep, require_no_orphans=False))
    out["ok"] = bool(out["zero_acked_loss"] and out["byte_identical"]
                     and out["full_node_answers_507"]
                     and out["doctor_saw_dead_peer"]
                     and out["census_clean"] and s["acked"] > 0)
    return out


# ------------------------------------------------------------------ #
# scenario 3: membership change DURING a partition
# ------------------------------------------------------------------ #

def scenario_ring_partition(h: ClusterHarness, p: dict) -> dict:
    load = LoadGen(h, p["payload"], rate_per_s=p["rate"], seed=44,
                   upload_nodes=[1, 2, 3], download_nodes=[1, 2, 3],
                   op_timeout_s=p["op_timeout"])
    load.run_for(p["warm_s"])
    h.set_chaos(1, partition="3")      # one-way: 1 -/-> 3 mid-change
    fault_thread = threading.Thread(
        target=load.run_for, args=(p["fault_s"],), daemon=True)
    fault_thread.start()
    time.sleep(0.5)
    # the membership change lands DURING the cut, on a node that can
    # still reach everyone — the epoch must gossip AROUND the partition
    # (node 1 learns it from 2/4 via epoch-on-RPC even though the push
    # from 2 reaches it directly here; node 3 likewise)
    add = h.ring_post(2, action="add", nodeId=4)
    fault_thread.join()
    h.set_chaos(1, partition="")       # heal
    load.drain()
    h.wait_ring_converged(add["epoch"], timeout=p["converge_s"])
    rep = h.wait_census_clean(1, timeout=p["converge_s"],
                              require_no_orphans=False)
    verify = load.verify_all(nodes=[1, 2, 3])
    s = load.snapshot()
    epochs = {i: h.ring_status(i).get("epoch")
              for i in range(1, h.n + 1)}
    out = {
        "acked": s["acked"],
        "ring_epoch": add["epoch"],
        "epochs_converged": all(e == add["epoch"]
                                for e in epochs.values()),
        "status_counts": s["status"],
        "verified": verify["ok"], "lost": verify["lost"],
        "zero_acked_loss": not verify["lost"],
        "byte_identical": (s["ack_hash_mismatch"] == 0
                           and s["download_mismatch"] == 0),
    }
    out.update(_census_gate(rep, require_no_orphans=False))
    out["ok"] = bool(out["zero_acked_loss"] and out["byte_identical"]
                     and out["epochs_converged"]
                     and out["census_clean"] and s["acked"] > 0)
    return out


# ------------------------------------------------------------------ #
# scenario 4: EC under faults — kill a shard holder mid-read
# ------------------------------------------------------------------ #

def scenario_ec_faults(h: ClusterHarness, p: dict) -> dict:
    # EC corpus (k=2: 2 data + P + Q across the 4 ring members)
    files: list[tuple[str, bytes]] = []
    for i in range(p["ec_files"]):
        data = os.urandom(p["ec_payload"])
        status, body = h.http(1, "POST", f"/upload?name=ec{i}.bin&ec=2",
                              body=data, timeout=p["op_timeout"])
        if status != 201:
            return {"ok": False, "error": f"ec upload {i} -> {status}: "
                                          f"{body[:200]!r}"}
        files.append((json.loads(body)["fileId"], data))

    decode0 = sum(_counter(h, i, "ec_decodes") for i in (1, 2, 4))
    reads = {"ok": 0, "bad": 0, "degraded": 0, "errors": 0}
    stop = threading.Event()

    def read_loop() -> None:
        i = 0
        while not stop.is_set():
            fid, data = files[i % len(files)]
            i += 1
            try:
                status, body = h.http(
                    2, "GET", f"/download?fileId={fid}",
                    timeout=p["op_timeout"])
            except OSError:
                reads["errors"] += 1
                continue
            if status == 200 and _sha256_hex(body) == fid:
                reads["ok"] += 1
            elif status == 200 and len(body) == len(data):
                # full-length body with the wrong bytes: CORRUPTION
                reads["bad"] += 1
            else:
                # error status / truncated stream (a node died mid-
                # body): degraded but honest — the client can tell
                reads["degraded"] += 1

    rt = threading.Thread(target=read_loop, daemon=True)
    rt.start()
    time.sleep(1.0)
    h.kill9(3)                       # a shard holder dies mid-read
    # reconstruction-under-load window: every EC file must read back
    # byte-identical from the survivors (parity decode), repeatedly
    t_end = time.time() + p["fault_s"]
    degraded_ok = True
    for rnd in range(100):
        if time.time() >= t_end and rnd >= 1:
            break
        for fid, data in files:
            status, body = h.http(4, "GET", f"/download?fileId={fid}",
                                  timeout=p["op_timeout"])
            if status != 200 or body != data:
                degraded_ok = False
    stop.set()
    rt.join(timeout=p["op_timeout"])
    decodes = sum(_counter(h, i, "ec_decodes")
                  for i in (1, 2, 4)) - decode0
    h.restart(3)
    rep = h.wait_census_clean(1, timeout=p["converge_s"],
                              require_no_orphans=False)
    out = {
        "ec_files": len(files),
        "degraded_reads_ok": degraded_ok,
        "background_reads": dict(reads),
        "background_read_corruptions": reads["bad"],
        "ec_decodes": decodes,
        "reconstruction_exercised": decodes > 0,
    }
    out.update(_census_gate(rep, require_no_orphans=False))
    out["ok"] = bool(degraded_ok and reads["bad"] == 0
                     and out["reconstruction_exercised"]
                     and out["census_clean"])
    return out


# ------------------------------------------------------------------ #
# scenario 5: hedged reads vs one intermittently slow replica
# ------------------------------------------------------------------ #

def _hedge_read_arm(h: ClusterHarness, files: list[str], p: dict
                    ) -> tuple[list[float], int]:
    """One measurement arm: the fixed read schedule from node 2 while
    node 3 pulses 250 ms of serve delay (p["pulse_duty"] of the time).
    Node 2, not node 1: under the static cyclic placement a 3-node
    rf=2 cluster's fully-remote digests seen from node 1 are exactly
    the {2,3}-owned ones — primary ALWAYS node 2 — so node 1 never
    routes a first fetch at node 3; node 2's remote digests are the
    {3,1}-owned ones, primary node 3, which is the read path a slow
    replica actually hurts. Returns (latencies, fetch RPCs issued by
    node 2)."""
    rpc0 = _fetch_rpc_count(h, 2)
    stop = threading.Event()

    def pulse() -> None:
        period = p["pulse_period_s"]
        on_s = period * p["pulse_duty"]
        while not stop.is_set():
            h.set_chaos(3, serve_delay_s=p["slow_s"])
            if stop.wait(on_s):
                break
            h.set_chaos(3, serve_delay_s=0.0)
            if stop.wait(period - on_s):
                break
        h.set_chaos(3, serve_delay_s=0.0)

    pt = threading.Thread(target=pulse, daemon=True)
    pt.start()
    lat: list[float] = []
    try:
        for _ in range(p["read_rounds"]):
            for fid in files:
                t0 = time.monotonic()
                status, body = h.http(2, "GET",
                                      f"/download?fileId={fid}",
                                      timeout=p["op_timeout"])
                took = time.monotonic() - t0
                if status != 200:
                    raise AssertionError(
                        f"hedge-arm read failed: {status}")
                lat.append(took)
    finally:
        stop.set()
        pt.join(timeout=10)
    lat.sort()
    return lat, _fetch_rpc_count(h, 2) - rpc0


def scenario_hedged_reads(h: ClusterHarness, p: dict) -> dict:
    # corpus from node 1: rf=2 owners among 3 nodes, so a fixed
    # fraction of every file's chunks reads remotely — and about half
    # of those route to the (pulsing-slow) node 3 first
    files: list[str] = []
    for i in range(p["hedge_files"]):
        data = os.urandom(p["hedge_payload"])
        status, body = h.http(1, "POST", f"/upload?name=h{i}.bin",
                              body=data, timeout=p["op_timeout"])
        if status != 201:
            return {"ok": False, "error": f"corpus upload -> {status}"}
        files.append(json.loads(body)["fileId"])

    # arm A: hedging OFF (the boot default) — the baseline tail + RPCs
    off_lat, off_rpcs = _hedge_read_arm(h, files, p)

    # arm B: same cluster, same data, every node rebooted with the
    # hedge budget armed; same pulse schedule, same read schedule
    for i in range(1, h.n + 1):
        h.restart(i, extra_flags=[
            "--hedge-budget", str(p["hedge_budget"]),
            "--hedge-floor", str(p["hedge_floor"]),
            "--hedge-cap", str(p["hedge_cap"])])
    on_lat, on_rpcs = _hedge_read_arm(h, files, p)
    hedge = ((h.metrics(2).get("serve") or {}).get("hedge")) or {}

    p99_off = percentile(off_lat, 0.99)
    p99_on = percentile(on_lat, 0.99)
    out = {
        "reads_per_arm": len(off_lat),
        "slow_replica": 3, "slow_s": p["slow_s"],
        "pulse_duty": p["pulse_duty"],
        "p50_off_s": round(percentile(off_lat, 0.50), 4),
        "p99_off_s": round(p99_off, 4),
        "p50_on_s": round(percentile(on_lat, 0.50), 4),
        "p99_on_s": round(p99_on, 4),
        "p99_cut_x": round(p99_off / p99_on, 2) if p99_on > 0 else 0.0,
        "rpcs_off": off_rpcs, "rpcs_on": on_rpcs,
        "rpc_ratio": round(on_rpcs / max(1, off_rpcs), 3),
        "hedge_fired": hedge.get("fired", 0),
        "hedge_won": hedge.get("won", 0),
    }
    out["ok"] = bool(out["p99_cut_x"] >= 2.0
                     and out["rpc_ratio"] <= 1.2
                     and out["hedge_fired"] > 0
                     and out["hedge_won"] > 0)
    return out


# ------------------------------------------------------------------ #
# driver
# ------------------------------------------------------------------ #

def run(tmp: Path, tiny: bool) -> dict:
    p = {
        # overload (gated 3-proc cluster)
        "payload": 24_000 if tiny else 96_000,
        "procs": 3,
        "capacity_threads": 8,
        "warm_s": 4.0 if tiny else 8.0,
        "overload_s": 6.0 if tiny else 15.0,
        "deadline_s": 6.0 if tiny else 8.0,
        "slo_p95_s": 12.0,
        "max_inflight": 1500,
        "drain_s": 12.0 if tiny else 25.0,
        # compound / ring_partition load
        "rate": 4.0 if tiny else 5.0,
        "fault_s": 4.0 if tiny else 10.0,
        "kill_delay_s": 0.25,
        # ec_faults
        "ec_files": 4 if tiny else 8,
        "ec_payload": 40_000 if tiny else 160_000,
        # hedged_reads
        # hedge files sized so EVERY read issues one batch to each
        # remote peer (>= ~8 chunks spread over both owner sets): the
        # fetch-RPC denominator then counts 2 per read and the <= 1.2x
        # budget bound is judged against the true fetch traffic
        "hedge_files": 6 if tiny else 10,
        "hedge_payload": 64_000 if tiny else 128_000,
        "read_rounds": 8 if tiny else 20,
        "slow_s": 0.25,
        "pulse_period_s": 1.2,
        "pulse_duty": 0.28,
        "hedge_budget": 50.0,
        "hedge_floor": 0.04,
        "hedge_cap": 0.3,
        "converge_s": 60.0 if tiny else 120.0,
        "op_timeout": 60.0 if tiny else 120.0,
    }
    out: dict = {"metric": "overload_survival", "round": 18,
                 "workload": {"tiny": tiny, **p}, "scenarios": {}}

    def run_one(name, fn, h):
        t0 = time.time()
        res = fn(h, p)
        res["seconds"] = round(time.time() - t0, 1)
        out["scenarios"][name] = res
        log(f"scenario {name}: ok={res.get('ok')} ({res['seconds']}s)")
        if not res.get("ok"):
            log(f"  detail: {json.dumps(res, default=str)[:900]}")

    # cluster A — gates ARMED + default deadline: overload, compound
    h = ClusterHarness(
        3, tmp / "gated", rf=2, repair_interval_s=1.0,
        extra_flags=["--download-slots", "6", "--upload-slots", "4",
                     "--internal-slots", "8", "--queue-depth", "8",
                     "--retry-after", "1",
                     "--default-deadline", str(p["deadline_s"])])
    try:
        h.start_all()
        h.wait_ready()
        run_one("overload", scenario_overload, h)
        run_one("compound", scenario_compound, h)
    finally:
        h.stop_all()

    # cluster B — 4-proc hash ring (members 1-3, node 4 standby):
    # ring_partition brings node 4 in; ec_faults then uses 4 members
    h2 = ClusterHarness(
        4, tmp / "ring", rf=2, repair_interval_s=1.0,
        extra_flags=["--ring-vnodes", "64", "--ring-members", "1,2,3"])
    try:
        h2.start_all()
        h2.wait_ready()
        run_one("ring_partition", scenario_ring_partition, h2)
        run_one("ec_faults", scenario_ec_faults, h2)
    finally:
        h2.stop_all()

    # cluster C — hedged-read measurement (chaos pulses, two arms)
    h3 = ClusterHarness(3, tmp / "hedge", rf=2, repair_interval_s=30.0)
    try:
        h3.start_all()
        h3.wait_ready()
        run_one("hedged_reads", scenario_hedged_reads, h3)
    finally:
        h3.stop_all()

    out["ok"] = all(s.get("ok") for s in out["scenarios"].values())
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tiny", action="store_true",
                    help="tier-1 smoke mode: short windows, small "
                         "payloads — same scenarios, same gates")
    ap.add_argument("--out", default=None,
                    help=f"artifact path (default: {ART} next to this "
                         "script)")
    args = ap.parse_args(argv)
    out_path = Path(args.out) if args.out \
        else Path(__file__).parent / ART
    with tempfile.TemporaryDirectory(prefix="bench_overload_") as tmp:
        out = run(Path(tmp), args.tiny)
    out_path.write_text(json.dumps(out, indent=2) + "\n")
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
