"""Async front of the local CAS (the write-path "disk tier").

Every ``ChunkStore`` operation is blocking file I/O; called inline from
the node's asyncio runtime it occupies the event loop for the syscall's
duration — under writeback pressure that measured multi-second stalls
during which the node answered nothing (the store_chunks receive path
learned this first, runtime._dispatch). This wrapper runs chunk
put/get through a small dedicated thread pool so

- the event loop never blocks on chunk file I/O, and
- disk concurrency is BOUNDED (``IngestConfig.cas_io_threads``) instead
  of riding the unbounded default ``asyncio.to_thread`` executor, which
  let a burst of concurrent reads stack arbitrary many file descriptors
  and seeks.

Batch variants (:meth:`put_many` / :meth:`get_many`) run a whole list in
ONE worker job — per-chunk executor dispatch costs a lock+wakeup per
item, which at CDC chunk sizes (thousands of chunks per batch) is real
time on the 1-core CI host.

The wrapper also attributes time: ``queue_s`` (submitted jobs waiting
for a free worker — the disk tier is saturated) vs ``busy_s`` (actual
I/O), surfaced under ``/metrics`` ``ingest.cas`` for the write-path
stall breakdown (docs/ingest.md).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Sequence, TypeVar

from dfs_tpu.store.cas import ChunkStore

T = TypeVar("T")


class AsyncChunkStore:
    """Bounded-thread-pool async wrapper over one node's :class:`ChunkStore`.

    Three lanes, because a batch job pins a worker for its whole list
    (thousands of chunk files — multi-second under writeback pressure)
    and FIFO queueing behind one would blow a peer RPC's budget, making
    a merely BUSY node look dead to its callers — the same
    probe-starvation failure the internal admission gate exempts health
    ops to avoid:

    - ``cas-w``: puts (ingest batches, handoff);
    - ``cas-r``: batched reads (``get_many`` — degraded-read gathers);
    - ``cas-g``: SINGLE-chunk gets (the peer-facing ``get_chunk``
      dispatch and ``_fetch_chunk``), so the latency-critical path
      never queues behind either batch lane.
    """

    def __init__(self, store: ChunkStore, workers: int = 4,
                 obs=None) -> None:
        self.store = store
        # Observability hook: when set, each op records a `cas.<op>`
        # span under the caller's trace context (the await happens on
        # the event-loop side, so ContextVar inheritance is free even
        # though run_in_executor itself does not copy contexts).
        self._obs = obs
        self._workers = max(1, int(workers))
        self._wpool = ThreadPoolExecutor(
            max_workers=self._workers, thread_name_prefix="cas-w")
        self._rpool = ThreadPoolExecutor(
            max_workers=self._workers, thread_name_prefix="cas-r")
        self._gpool = ThreadPoolExecutor(
            max_workers=max(2, self._workers // 2),
            thread_name_prefix="cas-g")
        self._lock = threading.Lock()
        self._ops = 0
        self._queue_s = 0.0
        self._busy_s = 0.0
        self._pending = 0   # submitted, not yet finished — the backlog
        # gauge the runtime sentinel samples (obs/sentinel.py): a value
        # persistently above the worker count means the disk tier is
        # saturated and callers are queueing

    async def _run(self, pool: ThreadPoolExecutor,
                   fn: Callable[[], T], opname: str | None = None) -> T:
        import asyncio

        t_submit = time.perf_counter()
        with self._lock:
            self._pending += 1

        def job() -> T:
            t_start = time.perf_counter()
            try:
                return fn()
            finally:
                t_end = time.perf_counter()
                with self._lock:
                    self._ops += 1
                    self._pending -= 1
                    self._queue_s += t_start - t_submit
                    self._busy_s += t_end - t_start

        loop = asyncio.get_running_loop()
        try:
            fut = loop.run_in_executor(pool, job)
        except BaseException:
            # submit failed (pool shut down): the job will never run its
            # finally, so the backlog gauge must be unwound here
            with self._lock:
                self._pending -= 1
            raise
        if self._obs is None or opname is None:
            return await fut
        with self._obs.span(opname):
            return await fut

    async def get(self, digest: str) -> bytes | None:
        return await self._run(self._gpool,
                               lambda: self.store.get(digest), "cas.get")

    async def put(self, digest: str, data: bytes,
                  verify: bool = False) -> bool:
        return await self._run(
            self._wpool,
            lambda: self.store.put(digest, data, verify=verify), "cas.put")

    async def has_many(self, digests: Sequence[str]) -> list[bool]:
        """Batched local existence — ONE worker job for the whole
        probe list. The ``has_chunks`` server path and the resume
        probe used to pay a per-digest job (or, worse, inline loop
        stats); a hot probe service must cost one worker dispatch per
        LIST. Each ``has`` rides the index fast path when the dedup
        plane is on (store/cas.py) and a stat otherwise. On the
        LATENCY lane (``cas-g``), not the batch-read lane: a probe is
        stats/index hits — microseconds — and peers time budget it
        like a metadata op, so it must never queue behind a
        multi-second ``get_many`` gather."""
        if not digests:
            return []
        ds = list(digests)
        return await self._run(
            self._gpool, lambda: self.store.has_many(ds), "cas.has_many")

    async def get_many(self, digests: Sequence[str]
                       ) -> list[tuple[str, bytes]]:
        """(digest, bytes) for every digest present locally — one worker
        job for the whole list; absent digests are simply missing."""
        if not digests:
            return []
        ds = list(digests)
        return await self._run(
            self._rpool,
            lambda: [(d, b) for d in ds
                     if (b := self.store.get(d)) is not None],
            "cas.get_many")

    async def put_many(self, items: Sequence[tuple[str, bytes]],
                       verify: bool = False) -> list[bool]:
        """Store a batch; per-item True = newly stored (False = dedup
        hit), same contract as :meth:`ChunkStore.put`, one worker job."""
        if not items:
            return []
        its = list(items)
        # put_batch, not a put loop: with the similarity plane attached
        # the store sketches the whole batch through the mesh in one
        # launch; without it, put_batch IS the per-item loop
        return await self._run(
            self._wpool,
            lambda: self.store.put_batch(its, verify=verify),
            "cas.put_many")

    async def inventory(self, list_prefixes=None,
                        list_cap: int = 4096) -> dict:
        """Bucketed CAS census scan (:meth:`ChunkStore.inventory`) as
        ONE read-pool job — a readdir+stat pass over the whole store
        (or, with ``list_prefixes``, a readdir of exactly those
        buckets — the drill-down never re-pays the full scan), which
        must ride the bounded batch lane like every other store-wide
        touch (a census fan-out must never occupy the event loop or
        stack unbounded executor jobs)."""
        lp = list(list_prefixes) if list_prefixes else None
        return await self._run(
            self._rpool,
            lambda: self.store.inventory(lp, list_cap=list_cap),
            "cas.inventory")

    @property
    def pending(self) -> int:
        """Jobs submitted but not yet finished (queued + running)."""
        with self._lock:
            return self._pending

    def stats(self) -> dict:
        with self._lock:
            return {"workers": self._workers, "ops": self._ops,
                    "pending": self._pending,
                    "queueS": round(self._queue_s, 6),
                    "busyS": round(self._busy_s, 6)}

    def close(self) -> None:
        # wait=False: in-flight jobs finish on their worker threads, but
        # an async stop() must not block its loop on the drain
        self._wpool.shutdown(wait=False)
        self._rpool.shutdown(wait=False)
        self._gpool.shutdown(wait=False)
