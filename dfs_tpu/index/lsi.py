"""Log-structured local digest index (the dedup/index plane's L0).

Every dedup decision today bottoms out in ``os.path.isfile`` — one stat
syscall per digest (store/cas.py ``has``), which is fine until the
catalog outgrows the dentry cache and every existence probe becomes a
disk seek (the Data Domain "disk bottleneck": Zhu et al., FAST'08).
This module is the memory-bounded on-disk fingerprint index that keeps
existence probes off the filesystem:

- an **append-only WAL** of (state, digest) records feeds a bounded
  in-memory **memtable** (dict, at most ``memtable_entries`` keys);
- a full memtable flushes to an immutable **sorted run** file; runs
  carry in-memory **fence pointers** (one 8-byte digest prefix per
  ``FENCE_EVERY`` records) and an optional per-run bloom, so a lookup
  is an O(1) memtable hit or ONE ``pread`` of a fenced block;
- when the run count exceeds ``compact_runs`` every run (plus the live
  memtable) folds into ONE base run — newest record wins, tombstones
  drop (a full compaction covers the whole keyspace, so "not found"
  and "deleted" are the same answer afterwards).

Crash safety is by ordering, not by fsync:

- the ``CURRENT`` manifest (atomic replace) is the only commitment
  point: runs and WALs it does not name do not exist — a crash mid
  flush/compaction leaves the previous CURRENT intact and the orphan
  files are swept at the next open;
- WAL records carry a per-record CRC; a torn tail (kill -9 mid-append)
  is truncated at the first bad record on replay;
- the feed ordering in ``ChunkStore`` (put recorded AFTER the link is
  visible, delete recorded BEFORE the unlink) makes every crash-window
  divergence a FALSE NEGATIVE — the index may not know about a chunk
  that exists (the stat backstop in ``ChunkStore.has`` covers it), but
  a "present" answer always refers to a chunk whose link was durable
  when the record was written. Put records may sit in a small buffer
  (flushed every ``_WAL_BUFFER`` records — losing them is the safe
  direction); delete records are written through before the unlink
  happens, because losing one would flip the divergence direction.

Anything structurally wrong at open (missing/corrupt CURRENT, bad run
checksum, impossible counts) degrades to a **rebuild from a CAS walk**
(``open_or_rebuild``) — the chunk files themselves are always the
ground truth, the index is a cache of their existence.

Thread discipline: every method is safe to call from the bounded CAS
worker threads (store/aio.py) — one lock guards the memtable/WAL/run
list; run files are immutable and read via ``os.pread`` on fds that
stay open until the run is retired, so lookups never race a
compaction's unlink.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import zlib
from bisect import bisect_right
from pathlib import Path
from typing import Callable, Iterable

from dfs_tpu.index.filter import BlockedBloomFilter
from dfs_tpu.utils.hashing import is_hex_digest

_RUN_MAGIC = 0x44495831            # "DIX1"
_RUN_HEADER = struct.Struct(">IHHQ")   # magic, version, reserved, count
_RUN_VERSION = 1
_REC = 33                          # 32 digest bytes + 1 state byte
_WAL_REC = 37                      # state + digest32 + crc32
_WAL_BUFFER = 256                  # put records buffered before a write
FENCE_EVERY = 1024                 # records per fenced block

_PRESENT = 1
_DELETED = 0
_PRESENT_COLD = 2   # present, demoted to the EC cold tier (r20) — a
                    # presence verdict for every reader (lookup /
                    # filters / compaction keep the record), distinct
                    # only for the tiering plane's bookkeeping


class _Run:
    """One immutable sorted run: an open fd + in-memory fences (+ bloom).

    ``fences[i]`` is the first 8 digest bytes (big-endian int) of record
    ``i * FENCE_EVERY``; a lookup bisects the fences, preads one block,
    and binary-searches the 33-byte records inside it.

    ``refs``/``retired`` are guarded by the OWNING index's lock: a
    lookup pins the runs it snapshots before releasing the lock to
    pread, and a compaction retires a run instead of closing it — the
    fd is disposed only once the last pinned reader drains, so an
    unlocked pread can never hit a closed (or worse, reused) fd.
    """

    def __init__(self, path: Path, fd: int, count: int,
                 fences: list[int], bloom: BlockedBloomFilter | None
                 ) -> None:
        self.path = path
        self.fd = fd
        self.count = count
        self.fences = fences
        self.bloom = bloom
        self.refs = 0          # pinned readers (owner lock)
        self.retired = False   # replaced by a compaction (owner lock)
        self.drop_file = True  # retirement unlinks (False at shutdown:
                               # the files ARE the persisted index)

    def dispose(self) -> None:
        """Close (+ unlink, per ``drop_file``) — owner lock held,
        ``refs == 0``."""
        self.close()
        if self.drop_file:
            try:
                self.path.unlink()
            except OSError:
                pass

    def get(self, raw: bytes, prefix: int) -> int | None:
        """State byte for ``raw`` (32-byte digest) or None if absent."""
        if self.bloom is not None and not self.bloom.contains_raw(raw):
            return None
        # rightmost fence <= prefix names the block that can hold the
        # digest (fences are the block FIRST keys)
        blk = bisect_right(self.fences, prefix) - 1
        while blk >= 0:
            first = blk * FENCE_EVERY
            n = min(FENCE_EVERY, self.count - first)
            if n <= 0:
                return None
            data = os.pread(self.fd, n * _REC,
                            _RUN_HEADER.size + first * _REC)
            lo, hi = 0, len(data) // _REC
            while lo < hi:
                mid = (lo + hi) // 2
                d = data[mid * _REC:mid * _REC + 32]
                if d < raw:
                    lo = mid + 1
                elif d > raw:
                    hi = mid
                else:
                    return data[mid * _REC + 32]
            # fences hold only 8-byte PREFIXES, which are ambiguous at
            # block boundaries: if this block's first prefix equals the
            # probe's, records with the same prefix but smaller
            # suffixes sort into the PREVIOUS block — walk back (loop:
            # a >1024-way prefix collision would span several blocks).
            # Missing this returned None from the newest run and let an
            # older run resurrect a tombstoned digest.
            if blk > 0 and self.fences[blk] == prefix:
                blk -= 1
                continue
            return None
        return None

    def records(self) -> Iterable[tuple[bytes, int]]:
        """(digest, state) pairs in sorted order — the merge input."""
        off = _RUN_HEADER.size
        left = self.count
        while left:
            n = min(left, 8192)
            data = os.pread(self.fd, n * _REC, off)
            for i in range(n):
                rec = data[i * _REC:(i + 1) * _REC]
                yield rec[:32], rec[32]
            off += n * _REC
            left -= n

    def close(self) -> None:
        try:
            os.close(self.fd)
        except OSError:
            pass


class DigestIndex:
    """Persistent, crash-safe, memory-bounded digest→presence index.

    ``hook`` is the chaos seam (same shape as ``ChunkStore.fault``):
    when set it is called with a crash-point name at the compaction
    commit edge, so the kill -9 crash tests / bench can die exactly
    mid-compaction. ``on_event(etype, **fields)`` is the journal hook
    the runtime wires to ``obs.event`` (index_rebuild / index_compact
    land in the flight recorder, trace-stamped).
    """

    def __init__(self, root: Path, memtable_entries: int = 65536,
                 compact_runs: int = 4, bloom_bits_per_key: int = 10,
                 background_compact: bool = False) -> None:
        self.root = Path(root)
        self.memtable_entries = max(256, int(memtable_entries))
        self.compact_runs = max(1, int(compact_runs))
        self.bloom_bits_per_key = max(0, int(bloom_bits_per_key))
        self.background_compact = bool(background_compact)
        self.hook: Callable[[str], None] | None = None
        self.on_event: Callable[..., None] | None = None
        # on_compact(present_digest_iter, count): the filter plane's
        # rebuild hook — a compaction is the one moment the full present
        # set is in hand, which is exactly when the local existence
        # filter can drop its accumulated deletes and bump generation
        self.on_compact: Callable[[list[bytes]], None] | None = None
        self._lock = threading.Lock()
        self._memtable: dict[bytes, int] = {}
        self._runs: list[_Run] = []
        self._wal_fd: int | None = None
        self._wal_name = ""
        self._wal_buf: list[bytes] = []
        self._seq = 0
        self._compacting = False
        self._compactions = 0
        self._rebuilds = 0
        self._wal_records = 0
        # background-compaction plumbing (ISSUE 16 satellite): the cv
        # shares the index lock, the thread starts lazily on the first
        # requested merge, and the stall counters attribute merge time
        # to whoever paid it — a CAS worker (inline mode: the r16
        # behavior, where one put froze behind a multi-second merge) or
        # the dedicated thread (background mode)
        self._compact_cv = threading.Condition(self._lock)
        self._compact_thread: threading.Thread | None = None
        self._compact_wanted = False
        self._closed = False
        self._compact_stall_s = 0.0   # merge seconds paid by callers
        self._bg_compact_s = 0.0      # merge seconds on the thread

    # ---------------------------------------------------------------- #
    # open / rebuild
    # ---------------------------------------------------------------- #

    def open_or_rebuild(self, cas_digests: Callable[[], list[str]]
                        ) -> dict:
        """Open the persisted index; on ANY structural damage fall back
        to a rebuild from ``cas_digests()`` (the CAS walk is ground
        truth). Returns {"rebuilt": bool, "entries": int, "runs": int,
        "reason": str | None}."""
        self.root.mkdir(parents=True, exist_ok=True)
        reason = None
        try:
            entries = self._open()
        except (OSError, ValueError, KeyError, struct.error,
                json.JSONDecodeError) as e:
            reason = f"{type(e).__name__}: {e}"
            entries = self._rebuild(cas_digests())
        # run-list length read under the lock: boot ordering makes an
        # unlocked read safe TODAY, but nothing pins open_or_rebuild to
        # run before the workers start (dfslint DFS008)
        with self._lock:
            nruns = len(self._runs)
        info = {"rebuilt": reason is not None, "entries": entries,
                "runs": nruns, "reason": reason}
        if reason is not None and self.on_event is not None:
            self.on_event("index_rebuild", entries=entries,
                          reason=reason[:160])
        return info

    def _open(self) -> int:
        cur_path = self.root / "CURRENT"
        strays = {p.name for p in self.root.iterdir()
                  if p.name != "CURRENT"}
        if not cur_path.is_file():
            if strays:
                # runs/WALs with no manifest: a crash before the very
                # first CURRENT write, or a deleted manifest — the
                # orphans are unnamed state, rebuild from ground truth
                raise ValueError("runs without a CURRENT manifest")
            self._init_fresh()
            return 0
        cur = json.loads(cur_path.read_bytes())
        runs = cur["runs"]
        wal = cur["wal"]
        if not isinstance(runs, list) or not isinstance(wal, str):
            raise ValueError("malformed CURRENT")
        with self._lock:
            for name in runs:
                self._runs.append(self._load_run(self.root / name))
            self._seq = 1 + max(
                [int(n.split("-")[1].split(".")[0]) for n in runs]
                + [int(wal.split("-")[1].split(".")[0])], default=0)
            self._wal_name = wal
            self._replay_wal(self.root / wal)
            self._wal_fd = os.open(self.root / wal,
                                   os.O_WRONLY | os.O_CREAT
                                   | os.O_APPEND, 0o600)
            # unnamed files are leftovers of a crashed flush/compaction
            for name in strays - set(runs) - {wal}:
                (self.root / name).unlink(missing_ok=True)
            return sum(r.count for r in self._runs) \
                + len(self._memtable)

    def _init_fresh(self) -> None:
        with self._lock:
            self._wal_name = f"wal-{self._seq:08d}.log"
            self._seq += 1
            self._wal_fd = os.open(self.root / self._wal_name,
                                   os.O_WRONLY | os.O_CREAT
                                   | os.O_APPEND, 0o600)
            self._write_current_locked()

    def _write_current_locked(self) -> None:
        data = json.dumps({"runs": [r.path.name for r in self._runs],
                           "wal": self._wal_name}).encode()
        tmp = self.root / ".CURRENT.tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, self.root / "CURRENT")

    def _load_run(self, path: Path) -> _Run:
        fd = os.open(path, os.O_RDONLY)
        try:
            head = os.pread(fd, _RUN_HEADER.size, 0)
            magic, version, _, count = _RUN_HEADER.unpack(head)
            if magic != _RUN_MAGIC or version != _RUN_VERSION:
                raise ValueError(f"bad run header in {path.name}")
            size = os.fstat(fd).st_size
            if size != _RUN_HEADER.size + count * _REC + 4:
                raise ValueError(f"run {path.name} size mismatch")
            # one sequential pass builds fences + bloom AND verifies the
            # footer checksum — the open-time cost that buys pread-only
            # lookups for the run's whole life
            fences: list[int] = []
            bloom = BlockedBloomFilter(count, self.bloom_bits_per_key) \
                if self.bloom_bits_per_key and count else None
            crc = 0
            off = _RUN_HEADER.size
            left = count
            i = 0
            while left:
                n = min(left, 8192)
                data = os.pread(fd, n * _REC, off)
                crc = zlib.crc32(data, crc)
                for j in range(n):
                    rec = data[j * _REC:(j + 1) * _REC]
                    if i % FENCE_EVERY == 0:
                        fences.append(int.from_bytes(rec[:8], "big"))
                    if bloom is not None:
                        bloom.add_raw(rec[:32])
                    i += 1
                off += n * _REC
                left -= n
            footer = os.pread(fd, 4, off)
            if len(footer) != 4 \
                    or int.from_bytes(footer, "big") != crc:
                raise ValueError(f"run {path.name} checksum mismatch")
            return _Run(path, fd, count, fences, bloom)
        except BaseException:
            os.close(fd)
            raise

    def _replay_wal(self, path: Path) -> None:
        try:
            data = path.read_bytes()
        except FileNotFoundError:
            return
        good = 0
        replayed: dict[bytes, int] = {}
        for off in range(0, len(data) - _WAL_REC + 1, _WAL_REC):
            rec = data[off:off + _WAL_REC]
            if zlib.crc32(rec[:33]) != int.from_bytes(rec[33:], "big"):
                break   # torn tail: everything after is untrusted
            replayed[rec[1:33]] = rec[0]
            good = off + _WAL_REC
        # replayed records are STRICTLY OLDER than anything already in
        # the memtable: a caller that noted before open() (nothing in
        # the runtime does since the boot reorder, but the seam does
        # not forbid it) must not have its verdicts overwritten by the
        # previous life's WAL
        for raw, state in replayed.items():
            self._memtable.setdefault(raw, state)
        self._wal_records = good // _WAL_REC
        if good != len(data):
            # truncate the torn tail so the next append starts clean
            with open(path, "r+b") as f:
                f.truncate(good)

    def _rebuild(self, digests: list[str]) -> int:
        """Reset to one sorted base run built from the CAS walk."""
        with self._lock:
            for r in self._runs:
                r.close()
            self._runs = []
            self._memtable = {}
            self._wal_buf = []
            if self._wal_fd is not None:
                os.close(self._wal_fd)
                self._wal_fd = None
            for p in list(self.root.iterdir()):
                # multi-step teardown without a crash point: a kill -9
                # anywhere in the rebuild leaves at worst NO CURRENT —
                # the next open starts empty and the stat backstop /
                # scrub walk (which triggered this rebuild) re-feeds
                # everything; the index is derived state by design
                p.unlink(missing_ok=True)  # dfslint: ignore[DFS013]
            self._seq = 0
            self._rebuilds += 1
            recs = sorted((bytes.fromhex(d), _PRESENT)
                          for d in digests if is_hex_digest(d))
            if recs:
                self._runs.append(self._write_run_locked(recs))
            self._wal_name = f"wal-{self._seq:08d}.log"
            self._seq += 1
            self._wal_fd = os.open(self.root / self._wal_name,
                                   os.O_WRONLY | os.O_CREAT
                                   | os.O_APPEND, 0o600)
            self._write_current_locked()
            if self.on_compact is not None:
                self.on_compact([d for d, _ in recs])
            return len(recs)

    # ---------------------------------------------------------------- #
    # feed (CAS worker threads)
    # ---------------------------------------------------------------- #

    def note_put(self, digest: str, defer_flush: bool = False) -> None:
        """Record a newly-visible chunk. Called AFTER the CAS link is
        durable-visible — a crash between link and record leaves a
        false NEGATIVE (stat backstop covers it), never a false
        positive. Buffered: losing the buffer is the same safe
        direction. ``defer_flush=True`` records WITHOUT the memtable
        flush/compaction trigger — the ChunkStore seam notes under its
        ordering mutex and runs :meth:`maybe_flush` after releasing
        it, so a multi-second merge never freezes every CAS worker
        behind one put."""
        self._note(digest, _PRESENT, wal_flush=False,
                   defer_flush=defer_flush)

    def note_delete(self, digest: str, defer_flush: bool = False
                    ) -> None:
        """Record a deletion. Called BEFORE the unlink and written
        through (unbuffered): losing a delete record would leave a
        stale "present" — the one divergence direction the design
        forbids. ``defer_flush`` as in :meth:`note_put` (the WAL
        write-through still happens inline — it is one buffered
        ``write``, not a merge)."""
        self._note(digest, _DELETED, wal_flush=True,
                   defer_flush=defer_flush)

    def note_tier(self, digest: str, cold: bool) -> None:
        """Record a tier flip (r20). Written through like a delete:
        the tier bit is flipped UNDER the demotion barrier (parity
        durable, replicas not yet dropped), so losing the record would
        leave the next life re-demoting an already-cold file — safe
        but wasteful; the write-through makes it merely unlikely. The
        WAL/run record format already round-trips arbitrary state
        bytes, so cold survives replay and compaction for free."""
        self._note(digest, _PRESENT_COLD if cold else _PRESENT,
                   wal_flush=True, defer_flush=False)

    def _note(self, digest: str, state: int, wal_flush: bool,
              defer_flush: bool) -> None:
        raw = bytes.fromhex(digest)
        body = bytes((state,)) + raw
        rec = body + zlib.crc32(body).to_bytes(4, "big")
        with self._lock:
            self._memtable[raw] = state
            self._wal_buf.append(rec)
            self._wal_records += 1
            if wal_flush or len(self._wal_buf) >= _WAL_BUFFER:
                self._flush_wal_locked()
            if not defer_flush:
                self._maybe_flush_locked()

    def maybe_flush(self) -> None:
        """Run the memtable-flush/compaction threshold check — the
        deferred half of ``defer_flush=True`` notes, called OUTSIDE
        the caller's ordering mutex."""
        with self._lock:
            self._maybe_flush_locked()

    def _maybe_flush_locked(self) -> None:
        # two triggers: distinct keys (memtable growth) and WAL
        # RECORDS — same-key churn (repeated store/delete of one
        # working set) rewrites memtable entries without growing the
        # dict, and an unbounded WAL would make replay time
        # proportional to total churn instead of catalog size
        if len(self._memtable) >= self.memtable_entries \
                or self._wal_records >= 8 * self.memtable_entries:
            self._flush_memtable_locked()

    def _flush_wal_locked(self) -> None:
        if self._wal_buf and self._wal_fd is not None:
            os.write(self._wal_fd, b"".join(self._wal_buf))
            self._wal_buf = []

    # ---------------------------------------------------------------- #
    # flush + compaction
    # ---------------------------------------------------------------- #

    def _write_run_locked(self, recs: list[tuple[bytes, int]]) -> _Run:
        """Allocate a sequence number and write one sorted run —
        callers hold the lock."""
        seq = self._seq
        self._seq += 1
        return self._write_run_file(recs, seq)

    def _write_run_file(self, recs: list[tuple[bytes, int]],
                        seq: int) -> _Run:
        """Write one sorted run (tmp + atomic rename) and return it
        loaded. Touches NO shared state (``seq`` is pre-allocated), so
        the off-lock compaction can call it while notes and lookups
        keep serving. ``recs`` must be sorted by digest."""
        name = f"run-{seq:08d}.idx"
        tmp = self.root / f".{name}.tmp"
        crc = 0
        with open(tmp, "wb") as f:
            f.write(_RUN_HEADER.pack(_RUN_MAGIC, _RUN_VERSION, 0,
                                     len(recs)))
            block: list[bytes] = []
            for raw, state in recs:
                block.append(raw + bytes((state,)))
                if len(block) >= 8192:
                    data = b"".join(block)
                    crc = zlib.crc32(data, crc)
                    f.write(data)
                    block = []
            if block:
                data = b"".join(block)
                crc = zlib.crc32(data, crc)
                f.write(data)
            f.write(crc.to_bytes(4, "big"))
        path = self.root / name
        os.replace(tmp, path)
        return self._load_run(path)

    def _flush_memtable_locked(self) -> None:
        """Memtable -> new run; commit via CURRENT; fresh WAL. Crash
        anywhere before the CURRENT replace: the old CURRENT still
        names the old WAL, which replays the same memtable."""
        if not self._memtable:
            return
        self._flush_wal_locked()
        recs = sorted(self._memtable.items())
        run = self._write_run_locked(recs)
        self._runs.append(run)
        old_wal = self._wal_name
        self._wal_name = f"wal-{self._seq:08d}.log"
        self._seq += 1
        new_fd = os.open(self.root / self._wal_name,
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o600)
        # multi-step sequence without its own crash point: every
        # interruption window is covered by the docstring's ordering
        # argument (before the replace the old CURRENT replays the old
        # WAL; after it the old-WAL unlink is idempotent cleanup), and
        # the compaction edge one level up fires the ``index.compact``
        # chaos seam kill tests drive
        self._write_current_locked()   # dfslint: ignore[DFS013]
        if self._wal_fd is not None:
            os.close(self._wal_fd)
        self._wal_fd = new_fd
        (self.root / old_wal).unlink(missing_ok=True)
        self._memtable = {}
        self._wal_records = 0
        self._request_compact_locked()

    def _request_compact_locked(self) -> None:
        """Route a due compaction: inline on the calling (CAS worker)
        thread — the historical behavior, its cost attributed to
        ``compactStallS`` — or handed to the dedicated thread when
        ``background_compact`` (the caller returns immediately; the
        worker never stalls behind the merge)."""
        if self._compacting or len(self._runs) <= self.compact_runs:
            return
        if self.background_compact:
            self._compact_wanted = True
            if self._compact_thread is None and not self._closed:
                self._compact_thread = threading.Thread(
                    target=self._compact_loop,
                    name="dfs-index-compact", daemon=True)
                self._compact_thread.start()
            self._compact_cv.notify_all()
            return
        t0 = time.monotonic()
        self._maybe_compact_locked()
        self._compact_stall_s += time.monotonic() - t0

    def _compact_loop(self) -> None:
        """Dedicated compaction thread: waits for a due merge, runs it,
        repeats. The chaos ``index.compact`` crash point now fires on
        this thread — SIGKILL semantics are process-wide, so the crash
        tests' commit-edge kill window is unchanged."""
        with self._lock:
            while True:
                while not self._compact_wanted and not self._closed:
                    self._compact_cv.wait()
                if self._closed:
                    return
                self._compact_wanted = False
                t0 = time.monotonic()
                self._maybe_compact_locked()
                self._bg_compact_s += time.monotonic() - t0
                self._compact_cv.notify_all()

    def drain_compaction(self) -> None:
        """Block until no compaction is pending or running — test /
        bench determinism; an inline-mode index returns immediately."""
        with self._lock:
            while self._compact_wanted or self._compacting:
                self._compact_cv.wait(timeout=0.05)

    def _maybe_compact_locked(self) -> None:
        """Fold every current run into one base run, newest record
        winning, tombstones dropped (full-keyspace compaction).

        The merge + new-run write — seconds for a large catalog — run
        WITHOUT the lock: the snapshot runs are immutable (pinned via
        refs so nothing disposes them), so notes and lookups keep
        serving while the merge streams; only the seq allocation, the
        run-list swap, and the CURRENT commit hold the lock. Runs
        flushed DURING the merge are newer than the snapshot and
        simply stay on top of the new base run; ``_compacting`` keeps
        a concurrent flush from starting a second merge. The chaos
        hook fires BEFORE the CURRENT commit — a kill -9 there leaves
        the old CURRENT naming the old runs, which the next open loads
        unharmed (the half-written new run is an unnamed stray).

        Lock contract: held on entry and on exit; released in the
        middle."""
        if self._compacting or len(self._runs) <= self.compact_runs:
            return
        self._compacting = True
        snapshot = list(self._runs)
        for r in snapshot:
            r.refs += 1
        seq = self._seq
        self._seq += 1
        self._lock.release()
        try:
            merged: dict[bytes, int] = {}
            # oldest first so newer runs overwrite older verdicts
            for run in snapshot:
                merged.update(run.records())
            recs = sorted((d, s) for d, s in merged.items()
                          if s != _DELETED)
            new_run = self._write_run_file(recs, seq)
            if self.hook is not None:
                self.hook("index.compact")
        except BaseException:
            self._lock.acquire()
            self._unpin_locked(snapshot)
            self._compacting = False
            raise
        self._lock.acquire()
        self._unpin_locked(snapshot)
        # the new base run takes the OLDEST position; anything flushed
        # during the merge stays newer (overrides it on lookup)
        self._runs = [new_run] + [r for r in self._runs
                                  if r not in snapshot]
        for r in snapshot:
            r.retired = True
            if r.refs == 0:
                r.dispose()
        self._write_current_locked()          # the commitment point
        self._compacting = False
        self._compactions += 1
        self._compact_cv.notify_all()         # wake drain_compaction
        # observer callbacks off the lock: the filter rebuild
        # (on_compact) is an O(entries) bloom build that must not
        # stall every note/lookup behind it
        self._lock.release()
        try:
            if self.on_event is not None:
                self.on_event("index_compact", runsFolded=len(snapshot),
                              entries=len(recs))
            if self.on_compact is not None:
                self.on_compact([d for d, _ in recs])
        finally:
            self._lock.acquire()

    # ---------------------------------------------------------------- #
    # lookups
    # ---------------------------------------------------------------- #

    def lookup(self, digest: str) -> bool:
        """True iff the index believes the chunk is present. False
        covers both "deleted" and "never heard of it" — after a full
        compaction the two are indistinguishable, and the caller's
        stat backstop treats them the same. Run preads happen OUTSIDE
        the lock against PINNED runs (see ``_Run``): a concurrent
        compaction retires runs instead of closing them under a
        reader."""
        if not is_hex_digest(digest):
            return False
        raw = bytes.fromhex(digest)
        prefix = int.from_bytes(raw[:8], "big")
        with self._lock:
            state = self._memtable.get(raw)
            if state is not None:
                return state != _DELETED
            runs = list(reversed(self._runs))   # newest first
            for r in runs:
                r.refs += 1
        try:
            for run in runs:
                state = run.get(raw, prefix)
                if state is not None:
                    return state != _DELETED
            return False
        finally:
            with self._lock:
                self._unpin_locked(runs)

    def _unpin_locked(self, runs) -> None:
        for r in runs:
            r.refs -= 1
            if r.retired and r.refs == 0:
                r.dispose()

    def present_digests(self) -> list[bytes]:
        """Every digest the index currently believes present (raw
        32-byte form) — the filter (re)build input. One merge pass;
        callers run it off the event loop."""
        with self._lock:
            merged: dict[bytes, int] = {}
            for run in self._runs:
                merged.update(run.records())
            merged.update(self._memtable)
        return [d for d, s in merged.items() if s != _DELETED]

    # ---------------------------------------------------------------- #
    # lifecycle / stats
    # ---------------------------------------------------------------- #

    def flush(self) -> None:
        """Write through the WAL buffer (tests / clean shutdown)."""
        with self._lock:
            self._flush_wal_locked()

    def close(self) -> None:
        # stop the compaction thread first (join OUTSIDE the lock — a
        # mid-merge thread needs the lock to commit before it exits);
        # a still-pending wanted-compaction is simply dropped: the run
        # files are the persisted index either way, and the next life
        # re-triggers the merge at its first flush
        with self._lock:
            self._closed = True
            self._compact_cv.notify_all()
            t = self._compact_thread
            self._compact_thread = None
        if t is not None:
            t.join(timeout=30.0)
        with self._lock:
            self._flush_wal_locked()
            if self._wal_fd is not None:
                os.close(self._wal_fd)
                self._wal_fd = None
            # RETIRE the runs instead of closing their fds outright:
            # the CAS pools shut down with wait=False, so an in-flight
            # has_many may still be pread()ing a pinned run — its
            # unpin disposes the fd when it drains. ``drop_file=False``:
            # shutdown keeps the run FILES (they are the persisted
            # index), unlike compaction retirement.
            for r in self._runs:
                r.retired = True
                r.drop_file = False
                if r.refs == 0:
                    r.dispose()
            self._runs = []

    def stats(self) -> dict:
        """/metrics ``index.lsi`` gauges. ``memtableBytes`` is the
        bounded structure's footprint estimate (keys + states + dict
        slots); the bench's 1M-catalog gate measures the real thing
        with tracemalloc."""
        with self._lock:
            fence_entries = sum(len(r.fences) for r in self._runs)
            bloom_bytes = sum(len(r.bloom.buf) for r in self._runs
                              if r.bloom is not None)
            return {
                "memtableEntries": len(self._memtable),
                "memtableBytes": len(self._memtable) * 93,
                "memtableCap": self.memtable_entries,
                "runCount": len(self._runs),
                "runEntries": sum(r.count for r in self._runs),
                "fenceBytes": fence_entries * 8,
                "runBloomBytes": bloom_bytes,
                "walRecords": self._wal_records,
                "compactions": self._compactions,
                "rebuilds": self._rebuilds,
                # stall attribution: merge seconds paid inline by CAS
                # workers vs on the dedicated thread — backgrounding is
                # working exactly when the first stays ~0 while the
                # second (and ``compactions``) grows
                "compactStallS": round(self._compact_stall_s, 6),
                "bgCompactS": round(self._bg_compact_s, 6),
            }
