"""End-to-end request deadlines (docs/serve.md §deadlines).

A deadline is born once at the HTTP edge — from the client's
``X-Dfs-Deadline: <seconds>`` header or ``ServeConfig.default_deadline_s``
— and rides a :mod:`contextvars` variable exactly like the r09 trace
context: every downstream hop of the request (placement tasks, the async
CAS pool, admission queue waits, RPC calls) inherits it without
plumbing, because ``asyncio.create_task`` / ``asyncio.to_thread`` copy
the context.

Representation: the context holds the ABSOLUTE ``time.monotonic()``
expiry. Crossing a process boundary it is re-encoded as the REMAINING
budget in seconds (the optional ``deadline`` wire-header field,
comm/wire.py) — absolute wall times would import the sender's clock
skew into the receiver's countdown; remaining-time hops lose only the
network flight time, which is exactly the decrement the hop cost.

Contract (the overload-survival plane, ROADMAP item 4): expired work
must never reach a worker thread. The RPC client refuses to start or
retry a call whose budget is gone; admission gates evict queued waiters
whose deadline passed (counted ``deadlineShed``, never plain ``shed``);
``_dispatch`` / ``_fetch_verified`` drop dead requests before touching
the CAS pool. No deadline set (the default — header absent AND
``default_deadline_s == 0``) means every check is one ContextVar read
returning None: pre-r18 behavior byte-identical.
"""

from __future__ import annotations

import contextvars
import math
import time

# absolute monotonic expiry of the current request, or None (no deadline
# — the default, and pre-r18 behavior exactly)
_ctx: contextvars.ContextVar[float | None] = \
    contextvars.ContextVar("dfs_deadline", default=None)

# a deadline asked to cover more than this is clamped: the field is
# operator/client input off the wire, and an absurd value (hours) would
# effectively disable the plane while looking enabled
MAX_DEADLINE_S = 3600.0


def activate(remaining_s: float) -> contextvars.Token:
    """Start a deadline ``remaining_s`` seconds from now for the current
    context; returns the token for :func:`restore`. A non-positive
    budget still activates (instantly expired) — the caller asked for
    it, and the drop paths are exactly what must fire."""
    remaining_s = min(float(remaining_s), MAX_DEADLINE_S)
    return _ctx.set(time.monotonic() + remaining_s)


def restore(token: contextvars.Token) -> None:
    _ctx.reset(token)


def clear() -> contextvars.Token:
    """Detach the current context from any deadline — for BACKGROUND
    work spawned from inside a request (``asyncio.create_task`` copies
    the context): a rebalance kicked by a deadlined RPC, say, must not
    inherit the request's dying budget. Returns the token in case the
    caller wants to restore; a task-level clear can drop it (the task's
    context dies with it)."""
    return _ctx.set(None)


def parse_header(value: str | None) -> float | None:
    """``X-Dfs-Deadline`` header value -> remaining seconds, or None for
    absent/malformed (never raises — a bad header must not fail the
    request it rides on, the X-Dfs-Trace discipline)."""
    if not value:
        return None
    try:
        s = float(value.strip())
    except ValueError:
        return None
    if not math.isfinite(s):
        return None
    return s


def parse_wire(field) -> float | None:
    """Wire-header ``deadline`` field -> remaining seconds, or None for
    absent/malformed (pre-r18 peers simply never send the field)."""
    if isinstance(field, bool) or not isinstance(field, (int, float)):
        return None
    if not math.isfinite(field):
        return None
    return float(field)


def remaining() -> float | None:
    """Seconds left on the active deadline (may be negative once
    expired), or None when no deadline is set."""
    exp = _ctx.get()
    if exp is None:
        return None
    return exp - time.monotonic()


def expired() -> bool:
    """True iff a deadline is set AND has passed. The no-deadline
    default answers False from one ContextVar read."""
    exp = _ctx.get()
    return exp is not None and time.monotonic() >= exp


__all__ = ["MAX_DEADLINE_S", "activate", "expired", "parse_header",
           "parse_wire", "remaining", "restore"]
