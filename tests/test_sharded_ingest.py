"""Sharded streaming CDC as an INGEST option: the
``FragmenterConfig.devices`` knob routes the ROLLING ``cdc`` strategy's
``stream.py`` regions through ``make_sharded_bitmap_step`` (round 10)
and the flagship ANCHORED strategy's region walk through the sharded
anchor/region passes with double-buffered staging (round 15), and the
resulting chunk boundaries and digests must be BYTE-IDENTICAL to the
single-device path — on smooth streams, ragged tails, carries crossing
region and device borders, empty and one-chunk streams, and through a
real node's streaming upload."""

import asyncio

import numpy as np
import pytest

from dfs_tpu.config import CDCParams, FragmenterConfig
from dfs_tpu.fragmenter.base import get_fragmenter
from dfs_tpu.fragmenter.cdc_anchored import AnchoredCpuFragmenter
from dfs_tpu.fragmenter.cdc_anchored_sharded import \
    ShardedAnchoredCdcFragmenter
from dfs_tpu.fragmenter.cdc_cpu import CpuCdcFragmenter, gear_bitmap_numpy
from dfs_tpu.fragmenter.cdc_sharded import ShardedCdcFragmenter
from dfs_tpu.ops.cdc_anchored import AnchoredCdcParams
from dfs_tpu.ops.cdc_v2 import AlignedCdcParams
from dfs_tpu.parallel.mesh import make_mesh
from dfs_tpu.parallel.sharded_cdc import (make_sharded_bitmap_step,
                                          shard_bitmap_inputs)
from dfs_tpu.utils.hashing import gear_table

PARAMS = CDCParams(min_size=64, avg_size=256, max_size=1024)
# tiny regions so the sharded step compiles fast on the CI host; still a
# multiple of the device count and >> the 31-byte halo
REGION = 4 * 4096

# anchored geometry: the anchored_sharded_parity_check shapes — 4 KiB
# lanes, 2-4 KiB segments; region = 4 device spans of one seg_max each
APARAMS = AnchoredCdcParams(
    chunk=AlignedCdcParams(min_blocks=2, avg_blocks=4, max_blocks=16,
                           strip_blocks=64),
    seg_min=2048, seg_max=4096, seg_mask=2047)
AREGION = 4 * 4096


def _frag(devices: int = 4) -> ShardedCdcFragmenter:
    return ShardedCdcFragmenter(
        PARAMS, FragmenterConfig(devices=devices, region_bytes=REGION))


def _blocks(data: bytes, n: int):
    for off in range(0, len(data), n):
        yield data[off:off + n]


def test_carry_bitmap_step_matches_oracle(rng):
    """The carry-in sharded bitmap == the whole-stream NumPy bitmap,
    region by region — including a NONZERO halo entering region 2."""
    table = gear_table(PARAMS.seed)
    mesh = make_mesh(4, dp=1)
    step = make_sharded_bitmap_step(mesh, table, PARAMS.mask)
    data = rng.integers(0, 256, size=2 * REGION, dtype=np.uint8)
    whole = gear_bitmap_numpy(data, table, PARAMS.mask)
    head = np.zeros((1, 31), dtype=np.uint32)
    for r in range(2):
        region = data[r * REGION:(r + 1) * REGION]
        bitmap = np.asarray(step(*shard_bitmap_inputs(
            mesh, region[None, :], head)))[0]
        assert np.array_equal(bitmap, whole[r * REGION:(r + 1) * REGION]), \
            f"region {r} bitmap diverged"
        head = table[region[-31:]].astype(np.uint32)[None, :]


@pytest.mark.parametrize("size", [0, 1, 5000, REGION, REGION + 1,
                                  3 * REGION - 7, 4 * REGION])
def test_sharded_stream_boundaries_byte_identical(rng, size):
    """manifest_stream through the sharded fragmenter == the CPU oracle:
    same spans, same digests, same file id — for empty, sub-region,
    exact-region, and ragged-tail stream lengths."""
    data = rng.integers(0, 256, size=size, dtype=np.uint8).tobytes()
    cpu = CpuCdcFragmenter(PARAMS).manifest_stream(
        _blocks(data, 1 << 14), name="x")
    shd = _frag().manifest_stream(_blocks(data, 1 << 14), name="x")
    assert [(c.offset, c.length, c.digest) for c in shd.chunks] \
        == [(c.offset, c.length, c.digest) for c in cpu.chunks]
    assert shd.file_id == cpu.file_id and shd.size == cpu.size


def test_sharded_stream_stores_identical_payloads(rng):
    data = rng.integers(0, 256, size=2 * REGION + 333,
                        dtype=np.uint8).tobytes()
    got: dict[str, bytes] = {}
    m = _frag().manifest_stream(_blocks(data, 8192), name="x",
                                store=lambda d, b: got.setdefault(d, b))
    assert b"".join(got[c.digest] for c in m.chunks) == data


def test_factory_returns_sharded_only_when_asked():
    frag = get_fragmenter("cdc", cdc_params=PARAMS,
                          frag=FragmenterConfig(devices=4,
                                                region_bytes=REGION))
    assert isinstance(frag, ShardedCdcFragmenter)
    # describe() (the resume protocol) is the CPU engine's — boundaries
    # are the same strategy, so a resuming client needs no new kind
    assert frag.describe()["kind"] == "cdc"
    single = get_fragmenter("cdc", cdc_params=PARAMS,
                            frag=FragmenterConfig())
    assert isinstance(single, CpuCdcFragmenter)
    assert not isinstance(single, ShardedCdcFragmenter)


def test_degraded_environment_falls_back(rng):
    """More devices configured than visible: ingest must still work,
    through the single-device kernel, with identical output."""
    frag = ShardedCdcFragmenter(
        PARAMS, FragmenterConfig(devices=64, region_bytes=64 * 124))
    data = rng.integers(0, 256, size=40_000, dtype=np.uint8).tobytes()
    cpu = CpuCdcFragmenter(PARAMS).manifest_stream(
        _blocks(data, 8192), name="x")
    shd = frag.manifest_stream(_blocks(data, 8192), name="x")
    assert frag._unavailable
    assert [(c.offset, c.length) for c in shd.chunks] \
        == [(c.offset, c.length) for c in cpu.chunks]


def test_node_streaming_upload_via_sharded_cdc(tmp_path, rng):
    """End to end: a single-node cluster configured with
    frag.devices=4 ingests a chunked-transfer stream through the sharded
    step and serves it back byte-identical."""
    from dfs_tpu.config import ClusterConfig, NodeConfig
    from dfs_tpu.node.runtime import StorageNodeServer

    data = rng.integers(0, 256, size=3 * REGION + 123,
                        dtype=np.uint8).tobytes()

    async def run():
        cluster = ClusterConfig.localhost(1, base_port=0,
                                          base_internal_port=0,
                                          replication_factor=1)
        import socket

        socks = [socket.socket() for _ in range(2)]
        for s in socks:
            s.bind(("127.0.0.1", 0))
        ports = [s.getsockname()[1] for s in socks]
        for s in socks:
            s.close()
        from dfs_tpu.config import PeerAddr
        cluster = ClusterConfig(
            peers=(PeerAddr(node_id=1, host="127.0.0.1", port=ports[0],
                            internal_port=ports[1]),),
            replication_factor=1)
        cfg = NodeConfig(
            node_id=1, cluster=cluster, data_root=tmp_path,
            fragmenter="cdc", cdc=PARAMS,
            frag=FragmenterConfig(devices=4, region_bytes=REGION),
            health_probe_s=0)
        node = StorageNodeServer(cfg)
        assert isinstance(node.fragmenter, ShardedCdcFragmenter)
        await node.start()
        try:
            async def blocks():
                for off in range(0, len(data), 8192):
                    yield data[off:off + 8192]

            manifest, _ = await node.upload_stream(blocks(), "s.bin")
            # boundaries equal the single-device oracle
            oracle = CpuCdcFragmenter(PARAMS).manifest_stream(
                _blocks(data, 8192), name="s.bin")
            assert [(c.offset, c.length, c.digest)
                    for c in manifest.chunks] \
                == [(c.offset, c.length, c.digest)
                    for c in oracle.chunks]
            _, got = await node.download(manifest.file_id)
            assert got == data
        finally:
            await node.stop()

    asyncio.run(run())


# ------------------------------------------------------------------ #
# ANCHORED sharded walk (round 15): the flagship pipeline's streaming
# region walk over the mesh — sharded pass A, host select with the
# threaded carry, sharded region step (repack/scan/digest per lane
# shard), double-buffered staging
# ------------------------------------------------------------------ #

def _afrag(devices: int = 4, region: int = AREGION,
           **kw) -> ShardedAnchoredCdcFragmenter:
    return ShardedAnchoredCdcFragmenter(
        APARAMS, FragmenterConfig(devices=devices, region_bytes=region),
        **kw)


@pytest.mark.parametrize("size", [0, 1, 100, 5000, AREGION, AREGION + 1,
                                  3 * AREGION - 7, 4 * AREGION,
                                  6 * AREGION + 12345])
def test_anchored_sharded_byte_identical(size):
    """manifest_stream through the sharded anchored walk == the host
    engine: same spans, same digests (device SHA vs host SHA-NI), same
    file id — for empty, one-chunk, sub-region, exact-region,
    multi-region and ragged-tail stream lengths."""
    rng = np.random.default_rng(4321)
    data = rng.integers(0, 256, size=size, dtype=np.uint8).tobytes()
    cpu = AnchoredCpuFragmenter(APARAMS, region_bytes=AREGION) \
        .manifest_stream(_blocks(data, 1 << 13), name="x")
    shd = _afrag().manifest_stream(_blocks(data, 1 << 13), name="x")
    assert [(c.offset, c.length, c.digest) for c in shd.chunks] \
        == [(c.offset, c.length, c.digest) for c in cpu.chunks]
    assert shd.file_id == cpu.file_id and shd.size == cpu.size


def test_anchored_sharded_carry_crosses_device_boundary():
    """The inter-region carry is NONZERO while consecutive windows of
    one batch live on DIFFERENT devices (windows ride the dp axis, one
    per device) — so the carried tail segment's bytes were staged to
    one device and its selection threads into the next device's window.
    The oracle (region_spans_np) derives the carry independently; the
    walk must reproduce the host engine exactly through that handoff."""
    from dfs_tpu.ops.cdc_anchored import region_spans_np

    rng = np.random.default_rng(4321)
    data = rng.integers(0, 256, size=3 * AREGION, dtype=np.uint8)
    _, consumed0 = region_spans_np(
        data[:AREGION], np.zeros((8,), np.uint8), 0, False, APARAMS)
    frag = _afrag()
    carry = consumed0 - frag.stride
    assert carry > 0, "chosen stream must leave a nonzero carry"
    # >1 device and >1 full window in the stream: windows 0 and 1 sit
    # on different mesh devices, and the carry crosses between them
    assert frag.devices > 1
    assert 3 * AREGION - frag.stride >= AREGION
    cpu = AnchoredCpuFragmenter(APARAMS, region_bytes=AREGION) \
        .manifest_stream(_blocks(data.tobytes(), 1 << 13), name="x")
    shd = frag.manifest_stream(_blocks(data.tobytes(), 1 << 13), name="x")
    assert [(c.offset, c.length, c.digest) for c in shd.chunks] \
        == [(c.offset, c.length, c.digest) for c in cpu.chunks]


def test_anchored_sharded_region_too_small_rejected():
    """A region that cannot hold two segments is a config error — the
    same two-segment floor the single-device walk enforces."""
    with pytest.raises(ValueError, match="two segments"):
        ShardedAnchoredCdcFragmenter(
            APARAMS, FragmenterConfig(devices=4, region_bytes=4096))


def test_anchored_sharded_stores_identical_payloads():
    rng = np.random.default_rng(4321)
    data = rng.integers(0, 256, size=2 * AREGION + 333,
                        dtype=np.uint8).tobytes()
    got: dict[str, bytes] = {}
    m = _afrag().manifest_stream(_blocks(data, 8192), name="x",
                                 store=lambda d, b: got.setdefault(d, b))
    assert b"".join(got[c.digest] for c in m.chunks) == data


def test_anchored_factory_returns_sharded_only_when_asked():
    frag = get_fragmenter("cdc-anchored", cdc_params=APARAMS,
                          frag=FragmenterConfig(devices=4,
                                                region_bytes=AREGION))
    assert isinstance(frag, ShardedAnchoredCdcFragmenter)
    # describe() (the resume protocol) is the host engine's — same
    # strategy, same boundaries, no new kind
    assert frag.describe()["kind"] == "cdc-anchored"
    single = get_fragmenter("cdc-anchored", cdc_params=APARAMS,
                            frag=FragmenterConfig())
    assert isinstance(single, AnchoredCpuFragmenter)
    assert not isinstance(single, ShardedAnchoredCdcFragmenter)


def test_anchored_sharded_degraded_environment_falls_back():
    """More devices configured than visible: ingest must still work,
    through the host region oracle, with identical output."""
    rng = np.random.default_rng(4321)
    frag = ShardedAnchoredCdcFragmenter(
        APARAMS, FragmenterConfig(devices=64, region_bytes=64 * 512))
    data = rng.integers(0, 256, size=50_000, dtype=np.uint8).tobytes()
    cpu = AnchoredCpuFragmenter(APARAMS).manifest_stream(
        _blocks(data, 8192), name="x")
    shd = frag.manifest_stream(_blocks(data, 8192), name="x")
    assert frag._unavailable
    assert [(c.offset, c.length, c.digest) for c in shd.chunks] \
        == [(c.offset, c.length, c.digest) for c in cpu.chunks]


def test_anchored_sharded_first_staging_sample_not_outlier():
    """r06 regression, sharded edition: the probe/step jits are warmed
    at step-build time, so the FIRST staging-bandwidth sample must not
    eat a trace/compile and read as an outlier vs the run's median.
    ``overlap_min_bw=inf`` keeps staging serial so EVERY window is
    timed (benches read the public surface; the raw samples are
    test-only)."""
    rng = np.random.default_rng(4321)
    frag = _afrag(overlap_min_bw=float("inf"))
    n_windows = 10
    total = AREGION + (n_windows - 1) * frag.stride
    data = rng.integers(0, 256, size=total, dtype=np.uint8).tobytes()
    assert frag.staging_timed_windows() == 0
    for _ in frag.chunks_stream(_blocks(data, 1 << 14)):
        pass
    assert frag.staging_timed_windows() >= n_windows - 1
    samples = list(frag._staging_samples)
    bws = [b / t for b, t in samples]
    med = sorted(bws)[len(bws) // 2]
    assert bws[0] >= med / 8, \
        f"first staging sample {bws[0]:.0f} B/s is an outlier vs " \
        f"median {med:.0f} B/s — a jit compile leaked into it"
    assert frag.reset_staging_samples() == len(samples)
    assert frag.staging_timed_windows() == 0


def test_node_streaming_upload_via_sharded_anchored(tmp_path):
    """End to end: a single-node cluster configured with
    fragmenter='cdc-anchored' + frag.devices selects the sharded walk
    (the config->factory path), and upload_stream through it serves
    back byte-identical data. The node's fragmenter is then swapped to
    the TEST geometry for the actual transfer — NodeConfig.cdc pins
    anchored strips to the production default, whose compile is the
    bench's job (CDC_SHARD_r15.json runs the real config geometry)."""
    from dfs_tpu.config import ClusterConfig, NodeConfig, PeerAddr
    from dfs_tpu.node.runtime import StorageNodeServer

    rng = np.random.default_rng(4321)
    data = rng.integers(0, 256, size=3 * AREGION + 123,
                        dtype=np.uint8).tobytes()

    async def run():
        import socket

        socks = [socket.socket() for _ in range(2)]
        for s in socks:
            s.bind(("127.0.0.1", 0))
        ports = [s.getsockname()[1] for s in socks]
        for s in socks:
            s.close()
        cluster = ClusterConfig(
            peers=(PeerAddr(node_id=1, host="127.0.0.1", port=ports[0],
                            internal_port=ports[1]),),
            replication_factor=1)
        cfg = NodeConfig(
            node_id=1, cluster=cluster, data_root=tmp_path,
            fragmenter="cdc-anchored",
            frag=FragmenterConfig(devices=4),
            health_probe_s=0)
        node = StorageNodeServer(cfg)
        assert isinstance(node.fragmenter, ShardedAnchoredCdcFragmenter)
        node.fragmenter = ShardedAnchoredCdcFragmenter(
            APARAMS, FragmenterConfig(devices=4, region_bytes=AREGION))
        await node.start()
        try:
            async def blocks():
                for off in range(0, len(data), 8192):
                    yield data[off:off + 8192]

            manifest, _ = await node.upload_stream(blocks(), "s.bin")
            oracle = AnchoredCpuFragmenter(
                APARAMS, region_bytes=AREGION).manifest_stream(
                _blocks(data, 8192), name="s.bin")
            assert [(c.offset, c.length, c.digest)
                    for c in manifest.chunks] \
                == [(c.offset, c.length, c.digest)
                    for c in oracle.chunks]
            assert not node.fragmenter._unavailable
            _, got = await node.download(manifest.file_id)
            assert bytes(got) == data
        finally:
            await node.stop()

    asyncio.run(run())


# ------------------------------------------------------------------ #
# tier-1 smoke: bench_cdc_sharded --tiny runs the sharded anchored walk
# at 1-2 devices + the full-node path and emits the CDC_SHARD_r15.json
# schema, locked against the committed artifact
# ------------------------------------------------------------------ #

def test_bench_cdc_sharded_tiny(tmp_path):
    import json
    import os
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    out_path = tmp_path / "CDC_SHARD_tiny.json"
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": str(repo)}
    proc = subprocess.run(
        [sys.executable, str(repo / "bench_cdc_sharded.py"),
         "--tiny", "--out", str(out_path)],
        cwd=tmp_path, env=env, capture_output=True, text=True,
        timeout=560)
    assert proc.returncode == 0, proc.stderr[-2000:]
    art = json.loads(out_path.read_text())
    committed = json.loads((repo / "CDC_SHARD_r15.json").read_text())
    # schema lock: the tiny artifact carries every top-level and
    # per-phase key the committed full-mode artifact commits to
    assert set(committed) <= set(art)
    assert set(committed["stream"]) <= set(art["stream"])
    assert set(committed["node"]) <= set(art["node"])
    assert art["metric"] == committed["metric"] == \
        "anchored_sharded_ingest"
    assert art["mode"] == "tiny" and art["ok"] is True
    s = art["stream"]
    assert len(s["devices"]) == len(s["gibps"]) == len(s["staging_gibps"])
    assert s["identical"] is True and s["reconstruction_ok"] is True
    assert art["node"]["byte_identical"] is True
    # perf is NOT gated in tiny mode (CI hosts stall unpredictably; the
    # committed artifact carries the >=1.7x scaling claim) — but the
    # committed FULL artifact must itself hold the gate
    assert committed["mode"] == "full" and committed["ok"] is True
    assert committed["stream"]["scale_max_devices"] >= 1.7
