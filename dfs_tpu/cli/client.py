"""Synchronous HTTP client — capability parity with client/src/Client.java.

Same helper surface as the reference's C7 (httpGetString/httpGetBytes/
httpPostString via HttpURLConnection with 5 s timeouts, Client.java:15,
278-340), built on urllib. Unlike the reference it also parses real JSON
instead of hand-scanning strings (Client.java:239-272)."""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass

DEFAULT_TIMEOUT_S = 5.0  # reference: 5000 ms, Client.java:15


@dataclass(frozen=True)
class RemoteFile:
    """Reference value type C8 (Client.java:19-27) + new metadata."""
    file_id: str
    name: str
    size: int = 0
    chunks: int = 0


class NodeClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 5001,
                 timeout_s: float = DEFAULT_TIMEOUT_S) -> None:
        self.base = f"http://{host}:{port}"
        self.timeout_s = timeout_s

    def _request(self, method: str, path: str, body: bytes | None = None,
                 headers: dict | None = None) -> bytes:
        req = urllib.request.Request(self.base + path, data=body,
                                     method=method,
                                     headers=headers or {})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return r.read()
        except urllib.error.HTTPError as e:
            detail = e.read().decode("utf-8", "replace")
            raise RuntimeError(f"HTTP {e.code}: {detail}") from e

    def status(self) -> str:
        return self._request("GET", "/status").decode()

    def list_files(self) -> list[RemoteFile]:
        items = json.loads(self._request("GET", "/files"))
        return [RemoteFile(file_id=i["fileId"], name=i.get("name", i["fileId"]),
                           size=i.get("size", 0), chunks=i.get("chunks", 0))
                for i in items]

    @staticmethod
    def _trace_headers(trace_id: str | None) -> dict:
        """``X-Dfs-Trace`` carrier for a client-minted trace id: the
        node tags every span the request causes (cluster-wide) with it,
        and :meth:`trace` stitches them afterwards."""
        if not trace_id:
            return {}
        from dfs_tpu.obs import new_span_id

        return {"X-Dfs-Trace": f"{trace_id}-{new_span_id()}"}

    def upload(self, data: bytes, name: str, ec: int = 0,
               trace_id: str | None = None) -> dict:
        params = {"name": name}
        if ec:
            params["ec"] = str(ec)
        q = urllib.parse.urlencode(params)
        return json.loads(self._request(
            "POST", f"/upload?{q}", body=data,
            headers=self._trace_headers(trace_id)))

    def upload_stream(self, blocks, name: str) -> dict:
        """Stream an upload with chunked transfer encoding (urllib sends
        chunked automatically for length-less iterables) — the node
        ingests it in bounded memory."""
        q = urllib.parse.urlencode({"name": name})
        return json.loads(self._request("POST", f"/upload?{q}",
                                        body=iter(blocks)))

    def chunking(self) -> dict:
        return json.loads(self._request("GET", "/chunking"))

    def missing(self, digests: list[str]) -> list[str]:
        body = json.dumps(digests).encode()
        return json.loads(self._request(
            "POST", "/missing", body=body))["missing"]

    def upload_resume(self, data: bytes, name: str,
                      trace_id: str | None = None) -> dict:
        """Resumable upload: chunk locally with the node's advertised
        parameters, probe which digests the cluster already holds, and
        transfer ONLY the missing payloads (plus the table). A re-POST
        of an interrupted upload therefore moves a small fraction of the
        body instead of every byte (SURVEY §5.4). Returns the node's
        upload reply plus 'clientBytesSent'. Falls back to a plain
        upload if the node's fragmenter is not resume-describable."""
        from dfs_tpu.fragmenter.base import fragmenter_from_description
        from dfs_tpu.utils.hashing import sha256_hex

        try:
            desc = self.chunking()
        except RuntimeError:
            out = self.upload(data, name, trace_id=trace_id)
            out["clientBytesSent"] = len(data)
            return out
        frag = fragmenter_from_description(desc["describe"])
        refs = frag.chunk(data)
        by_digest = {c.digest: c for c in refs}        # first occurrence
        missing = set(self.missing(list(by_digest)))
        provided = [(d, data[c.offset:c.offset + c.length])
                    for d, c in by_digest.items() if d in missing]
        meta = json.dumps({
            "fileId": sha256_hex(data),
            "size": len(data),
            "chunks": [[c.offset, c.length, c.digest] for c in refs],
            "provided": [d for d, _ in provided]}).encode()
        body = (len(meta).to_bytes(4, "big") + meta
                + b"".join(b for _, b in provided))
        q = urllib.parse.urlencode({"name": name})
        try:
            out = json.loads(self._request(
                "POST", f"/upload_resume?{q}", body=body,
                headers=self._trace_headers(trace_id)))
        except RuntimeError as e:
            if "HTTP 409" not in str(e):
                raise
            # a probed chunk vanished between /missing and the resume
            # (aged GC of unreferenced chunks, or its holder died) —
            # degrade to the plain full upload, as documented
            out = self.upload(data, name, trace_id=trace_id)
            out["clientBytesSent"] = len(body) + len(data)
            return out
        out["clientBytesSent"] = len(body)
        return out

    def download(self, file_id: str,
                 trace_id: str | None = None) -> bytes:
        q = urllib.parse.urlencode({"fileId": file_id})
        return self._request("GET", f"/download?{q}",
                             headers=self._trace_headers(trace_id))

    def download_range(self, file_id: str, start: int, end: int) -> bytes:
        """Bytes [start, end) via an HTTP Range request (206)."""
        q = urllib.parse.urlencode({"fileId": file_id})
        return self._request("GET", f"/download?{q}",
                             headers={"Range": f"bytes={start}-{end - 1}"})

    def scrub(self) -> dict:
        return json.loads(self._request("POST", "/scrub", body=b""))

    def manifest(self, file_id: str) -> dict:
        q = urllib.parse.urlencode({"fileId": file_id})
        return json.loads(self._request("GET", f"/manifest?{q}"))

    def metrics(self) -> dict:
        return json.loads(self._request("GET", "/metrics"))

    def metrics_prom(self) -> str:
        """Prometheus text exposition (GET /metrics?format=prom)."""
        return self._request("GET", "/metrics?format=prom").decode()

    def events(self, since: float = 0.0, limit: int = 256) -> dict:
        """Flight-recorder tail (GET /events): recent lifecycle events
        plus journal health counters (dropped/torn)."""
        q = urllib.parse.urlencode({"since": repr(float(since)),
                                    "limit": str(int(limit))})
        return json.loads(self._request("GET", f"/events?{q}"))

    def doctor(self, cluster: bool = True) -> dict:
        """Cluster doctor report (GET /doctor): per-node snapshots +
        named pathology findings — render with
        dfs_tpu.obs.doctor.render_report."""
        q = urllib.parse.urlencode({"cluster": "1" if cluster else "0"})
        return json.loads(self._request("GET", f"/doctor?{q}"))

    def census(self, cluster: bool = True) -> dict:
        """Replication-health census + capacity report (GET /census) —
        render with dfs_tpu.obs.census.render_census / render_df."""
        q = urllib.parse.urlencode({"cluster": "1" if cluster else "0"})
        return json.loads(self._request("GET", f"/census?{q}"))

    def history(self, name: str | None = None) -> dict:
        """Embedded metrics history (GET /metrics/history): the series
        directory, or one series' multi-resolution points."""
        path = "/metrics/history"
        if name:
            path += "?" + urllib.parse.urlencode({"name": name})
        return json.loads(self._request("GET", path))

    def ring_status(self, cluster: bool = True) -> dict:
        """Membership ring view (GET /ring): epoch, members, migration
        + rebalance state, peers' epoch views."""
        q = urllib.parse.urlencode({"cluster": "1" if cluster else "0"})
        return json.loads(self._request("GET", f"/ring?{q}"))

    def ring_admin(self, action: str, node_id: int | None = None,
                   weight: float | None = None) -> dict:
        """Membership change (POST /ring): add/drain/remove/reweight a
        member — the contacted node bumps the epoch, pushes the new
        map cluster-wide and kicks the online rebalancer."""
        body: dict = {"action": action}
        if node_id is not None:
            body["nodeId"] = node_id
        if weight is not None:
            body["weight"] = weight
        return json.loads(self._request(
            "POST", "/ring", body=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}))

    def trace(self, trace_id: str, cluster: bool = True) -> dict:
        """Spans of one trace, stitched cluster-wide by the contacted
        node (GET /trace) — render with dfs_tpu.obs.stitch.render_tree."""
        q = urllib.parse.urlencode({"traceId": trace_id,
                                    "cluster": "1" if cluster else "0"})
        return json.loads(self._request("GET", f"/trace?{q}"))

    def delete(self, file_id: str) -> str:
        q = urllib.parse.urlencode({"fileId": file_id})
        return self._request("DELETE", f"/files?{q}").decode()
