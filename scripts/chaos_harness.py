"""Cluster chaos harness: real-process nodes + open-loop load + fault
scripting (docs/chaos.md).

The library behind ``bench_chaos.py`` and ``tests/test_chaos.py``:

- :class:`ClusterHarness` — spins N separate ``dfs-tpu serve``
  processes (the reference's operating mode, the same shape
  tests/test_process_cluster.py runs), each booted with ``--chaos`` so
  scenarios can re-script fault knobs live via ``POST /chaos``; knows
  how to ``kill -9`` a node mid-flight and restart it (optionally with
  different flags — e.g. a crash point armed).
- :class:`LoadGen` — open-loop multi-tenant load: a scheduler thread
  issues uploads/downloads at a fixed rate REGARDLESS of completion
  (closed-loop generators throttle themselves exactly when the system
  degrades — hiding the overload the harness exists to provoke), with
  Zipf-distributed read popularity over the acked catalog. Every acked
  upload lands in a ledger keyed by its content hash; ``verify_all``
  later downloads every acked file and checks byte-identity (fileId IS
  sha256(body), so hash equality is byte equality) — the zero
  acked-write-loss invariant, mechanically checked.

Invariant doctrine (ROADMAP item 4): an upload that never acked may
vanish — its chunks are aged-GC orphans. An upload that ACKED (HTTP
201 whose fileId matches the locally computed content hash) must read
back byte-identical from any live node, through every fault this
harness can inject. That asymmetry is what fsync-before-ack buys.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _sha256_hex(data: bytes) -> str:
    from dfs_tpu.utils.hashing import sha256_hex

    return sha256_hex(data)


def _probe_free(port: int) -> bool:
    s = socket.socket()
    try:
        s.bind(("127.0.0.1", port))
        return True
    except OSError:
        return False
    finally:
        s.close()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def contiguous_free_ports(n: int) -> int:
    """cmd_serve derives peer ports as base+i; find a free run of n."""
    for _ in range(50):
        base = _free_port()
        if all(_probe_free(base + i) for i in range(n)):
            return base
    raise RuntimeError("no contiguous free port run found")


class HarnessError(AssertionError):
    """A scenario precondition/invariant the harness could not meet."""


class ClusterHarness:
    """N real ``dfs-tpu serve`` processes with the chaos plane armed."""

    def __init__(self, n: int, workdir: Path, rf: int = 2,
                 repair_interval_s: float = 1.0,
                 extra_flags: list[str] | None = None,
                 chaos: bool = True, env: dict | None = None) -> None:
        self.n = n
        self.rf = rf
        self.workdir = Path(workdir)
        self.workdir.mkdir(parents=True, exist_ok=True)
        base = contiguous_free_ports(2 * n)
        self.base_http = base
        self.base_internal = base + n
        self.repair_interval_s = repair_interval_s
        self.extra_flags = list(extra_flags or [])
        self.chaos = chaos
        self.env = {**os.environ, "JAX_PLATFORMS": "cpu",
                    "PYTHONPATH": str(REPO), **(env or {})}
        self.procs: dict[int, subprocess.Popen] = {}
        # per-node flag overrides applied at (re)start — scenarios arm
        # crash points by restarting a node with different flags
        self._node_flags: dict[int, list[str]] = {}

    # ---- lifecycle --------------------------------------------------- #

    def http_port(self, node_id: int) -> int:
        return self.base_http + node_id - 1

    def _argv(self, node_id: int) -> list[str]:
        argv = [sys.executable, "-m", "dfs_tpu.cli.main", "serve",
                "--node-id", str(node_id), "--nodes", str(self.n),
                "--base-port", str(self.base_http),
                "--base-internal-port", str(self.base_internal),
                "--replication-factor", str(self.rf),
                "--fragmenter", "cdc",
                "--data-root", str(self.workdir / "data"),
                "--repair-interval", str(self.repair_interval_s),
                "--probe-interval", "2"]
        if self.chaos:
            argv += ["--chaos"]
        argv += self.extra_flags
        argv += self._node_flags.get(node_id, [])
        return argv

    def start(self, node_id: int,
              extra_flags: list[str] | None = None) -> None:
        if extra_flags is not None:
            self._node_flags[node_id] = list(extra_flags)
        log = (self.workdir / f"node{node_id}.log").open("ab")
        self.procs[node_id] = subprocess.Popen(
            self._argv(node_id), cwd=self.workdir, env=self.env,
            stdout=log, stderr=subprocess.STDOUT)

    def start_all(self) -> None:
        for i in range(1, self.n + 1):
            self.start(i)

    def wait_ready(self, node_ids=None, timeout: float = 90.0,
                   respawns: int = 2) -> None:
        deadline = time.time() + timeout
        for i in (node_ids or range(1, self.n + 1)):
            tries = 0
            while True:
                p = self.procs.get(i)
                if p is not None and p.poll() is not None:
                    tail = self.node_log(i)[-2000:]
                    if "address already in use" in tail \
                            and tries < respawns:
                        # bind(0)-allocated harness ports sit in the
                        # ephemeral range: any process's OUTBOUND
                        # connection can squat one before the node
                        # binds it. Squatters are short-lived —
                        # re-spawn after a beat (same flags).
                        tries += 1
                        time.sleep(1.5)
                        self.start(i)
                        continue
                    raise HarnessError(
                        f"node {i} died during startup: " + tail)
                try:
                    status, body = self.http(i, "GET", "/status",
                                             timeout=2)
                    if status == 200 and body == b"OK":
                        break
                except OSError:
                    pass
                if time.time() > deadline:
                    raise HarnessError(f"node {i} never came up: "
                                       + self.node_log(i)[-2000:])
                time.sleep(0.2)

    def kill9(self, node_id: int) -> None:
        """kill -9: no shutdown path runs — what fsync-before-ack must
        survive. Idempotent on an already-dead node."""
        p = self.procs.get(node_id)
        if p is None or p.poll() is not None:
            return
        os.kill(p.pid, signal.SIGKILL)
        p.wait(timeout=10)

    def wait_dead(self, node_id: int, timeout: float = 30.0) -> int:
        """Block until the node process exits (a crash point firing);
        returns the negative signal number / exit code."""
        p = self.procs[node_id]
        return p.wait(timeout=timeout)

    def restart(self, node_id: int,
                extra_flags: list[str] | None = None,
                timeout: float = 90.0, attempts: int = 3) -> None:
        flags = extra_flags if extra_flags is not None else []
        for a in range(attempts):
            self.kill9(node_id)
            self.start(node_id, extra_flags=flags)
            try:
                self.wait_ready([node_id], timeout=timeout)
                return
            except HarnessError:
                # while the node was dead, any process's OUTBOUND
                # connection may have landed on its port as an
                # ephemeral source (harness ports come from bind(0)) —
                # the reborn node then dies with EADDRINUSE. Ephemeral
                # squatters are short-lived: wait a beat and re-spawn.
                if a + 1 >= attempts:
                    raise
                time.sleep(1.5)

    def stop_all(self) -> None:
        for p in self.procs.values():
            if p.poll() is None:
                p.terminate()
        for p in self.procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()

    def node_log(self, node_id: int) -> str:
        try:
            return (self.workdir / f"node{node_id}.log").read_text(
                errors="replace")
        except OSError:
            return ""

    # ---- HTTP -------------------------------------------------------- #

    def http(self, node_id: int, method: str, path: str,
             body: bytes | None = None, headers: dict | None = None,
             timeout: float = 60.0) -> tuple[int, bytes]:
        """One HTTP request to a node; HTTP errors return (status,
        body) instead of raising — a 503/507 is scenario DATA, not a
        harness failure. Transport errors (dead node) raise OSError."""
        status, data, _ = self.http_h(node_id, method, path, body=body,
                                      headers=headers, timeout=timeout)
        return status, data

    def http_h(self, node_id: int, method: str, path: str,
               body: bytes | None = None, headers: dict | None = None,
               timeout: float = 60.0) -> tuple[int, bytes, dict]:
        """:meth:`http` plus the response headers (lower-cased keys) —
        scenarios that honor ``Retry-After`` need them."""
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.http_port(node_id)}{path}",
            data=body, method=method, headers=headers or {})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, r.read(), \
                    {k.lower(): v for k, v in r.headers.items()}
        except urllib.error.HTTPError as e:
            return e.code, e.read(), \
                {k.lower(): v for k, v in e.headers.items()}

    def get_json(self, node_id: int, path: str,
                 timeout: float = 60.0) -> dict:
        status, body = self.http(node_id, "GET", path, timeout=timeout)
        if status != 200:
            raise HarnessError(f"GET {path} on node {node_id} -> "
                               f"{status}: {body[:200]!r}")
        return json.loads(body)

    def set_chaos(self, node_id: int, **knobs) -> dict:
        status, body = self.http(
            node_id, "POST", "/chaos",
            body=json.dumps(knobs).encode(),
            headers={"Content-Type": "application/json"}, timeout=30)
        if status != 200:
            raise HarnessError(f"POST /chaos on node {node_id} -> "
                               f"{status}: {body[:200]!r}")
        return json.loads(body)

    def metrics(self, node_id: int) -> dict:
        return self.get_json(node_id, "/metrics")

    # ---- membership ring (docs/membership.md) ------------------------ #

    def ring_status(self, node_id: int, cluster: bool = False) -> dict:
        return self.get_json(
            node_id, f"/ring?cluster={'1' if cluster else '0'}")

    def ring_post(self, node_id: int, **body) -> dict:
        """POST /ring membership change on one node (it pushes the new
        epoch cluster-wide and kicks the rebalancer)."""
        status, resp = self.http(
            node_id, "POST", "/ring", body=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, timeout=60)
        if status != 200:
            raise HarnessError(f"POST /ring on node {node_id} -> "
                               f"{status}: {resp[:200]!r}")
        return json.loads(resp)

    def wait_ring_converged(self, epoch: int, node_ids=None,
                            timeout: float = 90.0) -> None:
        """Block until every named node reports the epoch AND has
        closed its migration window (rebalance_done) — the moment
        dual-read ends and placement is steady-state again."""
        deadline = time.time() + timeout
        pending = list(node_ids or range(1, self.n + 1))
        while pending and time.time() < deadline:
            still = []
            for i in pending:
                try:
                    st = self.ring_status(i)
                    if st.get("epoch") != epoch or st.get("migrating"):
                        still.append(i)
                except (OSError, HarnessError):
                    still.append(i)
            pending = still
            if pending:
                time.sleep(0.5)
        if pending:
            raise HarnessError(
                f"nodes {pending} never converged to ring epoch "
                f"{epoch} within {timeout}s: "
                + "; ".join(self.node_log(i)[-500:] for i in pending))

    def census(self, node_id: int) -> dict:
        return self.get_json(node_id, "/census", timeout=120)

    def doctor(self, node_id: int) -> dict:
        return self.get_json(node_id, "/doctor", timeout=120)

    def trace(self, node_id: int, trace_id: str) -> dict:
        return self.get_json(node_id, f"/trace?traceId={trace_id}")

    def wait_census_clean(self, node_id: int, timeout: float = 60.0,
                          require_no_orphans: bool = True) -> dict:
        """Poll /census until the repair loop has converged the data
        plane: no under-/over-replication, all peers answering (and,
        unless the scenario aborted uploads, no orphans). Returns the
        final report either way — the caller gates on it."""
        deadline = time.time() + timeout
        rep: dict = {}
        while time.time() < deadline:
            try:
                rep = self.census(node_id)
            except (OSError, HarnessError):
                time.sleep(1.0)
                continue
            clean = (rep.get("peersFailed", 1) == 0
                     and rep.get("underReplicatedTotal", 1) == 0
                     and rep.get("overReplicatedTotal", 1) == 0
                     and (not require_no_orphans
                          or rep.get("orphanedTotal", 1) == 0))
            if clean:
                return rep
            time.sleep(1.0)
        return rep


class LoadGen:
    """Open-loop, multi-tenant Zipf load against a ClusterHarness.

    A scheduler thread fires one operation every ``1/rate_per_s``
    seconds into a worker pool, never waiting for completions (open
    loop: offered load is independent of system health). Uploads carry
    fresh pseudo-random payloads; the ack ledger records
    ``fileId == sha256(payload)`` — an ack whose fileId does NOT match
    the locally computed hash is counted as a corruption, not an ack.
    Downloads pick a ledger entry with Zipf(popularity by recency) and
    verify the body hashes to its fileId. Status-code counts are kept
    per class so a scenario can assert e.g. "zero 503s" or "507s only
    on the disk-full node"."""

    # Retry-After discipline (docs/chaos.md): a 503-shed op is retried
    # AFTER the server-advertised budget with DECORRELATED JITTER —
    # sleep_n = min(CAP, uniform(retry_after, 3 x sleep_{n-1})). An
    # immediate retry would turn one shed into a synchronized retry
    # storm: every shed client re-arriving together is exactly the
    # thundering herd the 503 was trying to disperse.
    RETRY_503_MAX = 2          # retries per op beyond the first attempt
    RETRY_503_CAP_S = 10.0     # worst-case single backoff sleep

    def __init__(self, harness: ClusterHarness, payload_bytes: int,
                 rate_per_s: float = 6.0, tenants: int = 3,
                 upload_fraction: float = 0.5, seed: int = 1234,
                 upload_nodes=None, download_nodes=None,
                 op_timeout_s: float = 60.0,
                 retry_503: int | None = None) -> None:
        import random as _random

        self.h = harness
        self.payload_bytes = payload_bytes
        self.interval = 1.0 / rate_per_s
        self.tenants = tenants
        self.upload_fraction = upload_fraction
        self.op_timeout_s = op_timeout_s
        self.retry_503 = self.RETRY_503_MAX if retry_503 is None \
            else int(retry_503)
        self._rng = _random.Random(seed)
        # injectable for tests: the Retry-After backoff sleeps through
        # this, so a unit test can record delays instead of waiting
        self._sleep = time.sleep
        self._nodes_up = list(upload_nodes
                              or range(1, harness.n + 1))
        self._nodes_down = list(download_nodes
                                or range(1, harness.n + 1))
        self._lock = threading.Lock()
        self.ledger: list[dict] = []      # acked: {fileId, size, node}
        self.stats = {"uploads_attempted": 0, "uploads_acked": 0,
                      "uploads_failed": 0, "ack_hash_mismatch": 0,
                      "downloads_attempted": 0, "downloads_ok": 0,
                      "downloads_failed": 0, "download_mismatch": 0,
                      "retries_503": 0,
                      "status": {}}
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._seq = 0

    def _jitter_503(self, retry_after_s: float,
                    prev_s: float | None) -> float:
        """Next decorrelated-jitter sleep after a 503 — delegates to
        the ONE module-level rule (:func:`_decorrelated_503_sleep`) so
        the threaded and multi-process generators cannot silently
        diverge on the retry discipline."""
        with self._lock:
            return _decorrelated_503_sleep(self._rng, retry_after_s,
                                           prev_s,
                                           cap_s=self.RETRY_503_CAP_S)

    def _request_with_503_retry(self, node: int, method: str, path: str,
                                body: bytes | None = None,
                                headers: dict | None = None
                                ) -> tuple[int, bytes]:
        """One op with Retry-After-honoring 503 retries. Raises OSError
        on transport failure exactly like :meth:`ClusterHarness.http`."""
        prev: float | None = None
        for attempt in range(1 + self.retry_503):
            status, data, hdrs = self.h.http_h(
                node, method, path, body=body, headers=headers,
                timeout=self.op_timeout_s)
            if status != 503 or attempt >= self.retry_503:
                return status, data
            self._count_status(503)   # retried sheds still show in the
            # per-status table; the caller counts the FINAL status
            try:
                ra = float(hdrs.get("retry-after", 1.0))
            except ValueError:
                ra = 1.0
            with self._lock:
                self.stats["retries_503"] += 1
            prev = self._jitter_503(ra, prev)
            self._sleep(prev)
        return status, data

    # ---- ops --------------------------------------------------------- #

    def _payload(self, tenant: int, seq: int) -> bytes:
        import numpy as np

        rng = np.random.default_rng((tenant << 32) ^ seq ^ 0xC4A05)
        return rng.integers(0, 256, size=self.payload_bytes,
                            dtype=np.uint8).tobytes()

    def _count_status(self, status: int) -> None:
        with self._lock:
            key = str(status)
            self.stats["status"][key] = \
                self.stats["status"].get(key, 0) + 1

    def _upload_once(self, tenant: int, seq: int, node: int,
                     trace_id: str | None = None) -> dict | None:
        data = self._payload(tenant, seq)
        want = _sha256_hex(data)
        with self._lock:
            self.stats["uploads_attempted"] += 1
        headers = {"Content-Type": "application/octet-stream"}
        if trace_id is not None:
            headers["X-Dfs-Trace"] = f"{trace_id}-{os.urandom(8).hex()}"
        try:
            status, body = self._request_with_503_retry(
                node, "POST", f"/upload?name=t{tenant}%2Ff{seq}.bin",
                body=data, headers=headers)
        except OSError:
            with self._lock:
                self.stats["uploads_failed"] += 1
            return None
        self._count_status(status)
        if status != 201:
            with self._lock:
                self.stats["uploads_failed"] += 1
            return None
        info = json.loads(body)
        if info.get("fileId") != want:
            # the server acked bytes OTHER than what was sent — a
            # corruption-class failure, never a mere op error
            with self._lock:
                self.stats["ack_hash_mismatch"] += 1
            return None
        entry = {"fileId": want, "size": len(data), "node": node,
                 "tenant": tenant}
        with self._lock:
            self.stats["uploads_acked"] += 1
            self.ledger.append(entry)
        return entry

    def _download_once(self, entry: dict, node: int) -> bool:
        with self._lock:
            self.stats["downloads_attempted"] += 1
        try:
            status, body = self._request_with_503_retry(
                node, "GET", f"/download?fileId={entry['fileId']}")
        except OSError:
            with self._lock:
                self.stats["downloads_failed"] += 1
            return False
        self._count_status(status)
        if status != 200:
            with self._lock:
                self.stats["downloads_failed"] += 1
            return False
        if len(body) != entry["size"] \
                or _sha256_hex(body) != entry["fileId"]:
            with self._lock:
                self.stats["download_mismatch"] += 1
            return False
        with self._lock:
            self.stats["downloads_ok"] += 1
        return True

    def _pick_zipf(self) -> dict | None:
        """Zipf-by-recency over the acked catalog: rank 1 = newest,
        p(rank) ∝ 1/rank^1.2 — the hot-head/long-tail read mix."""
        with self._lock:
            n = len(self.ledger)
            if n == 0:
                return None
            weights = [1.0 / (r ** 1.2) for r in range(1, n + 1)]
            total = sum(weights)
            x = self._rng.random() * total
            acc = 0.0
            for rank, w in enumerate(weights, 1):
                acc += w
                if x <= acc:
                    return self.ledger[n - rank]
            return self.ledger[0]

    # ---- open loop --------------------------------------------------- #

    def _one_op(self) -> None:
        if self._rng.random() < self.upload_fraction or not self.ledger:
            tenant = self._rng.randrange(self.tenants)
            with self._lock:
                self._seq += 1
                seq = self._seq
            self._upload_once(tenant, seq,
                              self._rng.choice(self._nodes_up))
        else:
            entry = self._pick_zipf()
            if entry is not None:
                self._download_once(entry,
                                    self._rng.choice(self._nodes_down))

    def run_for(self, seconds: float) -> None:
        """Open-loop burst: fire ops on schedule for ``seconds``, then
        wait for the in-flight stragglers."""
        deadline = time.time() + seconds
        while time.time() < deadline and not self._stop.is_set():
            t = threading.Thread(target=self._one_op, daemon=True)
            t.start()
            self._threads.append(t)
            time.sleep(self.interval)
        self.drain()

    def drain(self, timeout: float = 120.0) -> None:
        deadline = time.time() + timeout
        for t in self._threads:
            t.join(timeout=max(0.1, deadline - time.time()))
        self._threads = [t for t in self._threads if t.is_alive()]

    # ---- invariants -------------------------------------------------- #

    def verify_all(self, nodes=None, timeout_per_file: float = 60.0
                   ) -> dict:
        """THE invariant: every acked upload downloads byte-identical
        (sha256(body) == fileId) from a live node. Returns
        {checked, ok, lost: [fileIds]}. Verification reads go through
        ``_download_once`` so they keep counting into this generator's
        stats (the r13 artifact shape)."""
        with self._lock:
            entries = list(self.ledger)
        return verify_ledger(self.h, entries, nodes=nodes,
                             timeout_per_file=timeout_per_file,
                             download=self._download_once)

    def snapshot(self) -> dict:
        with self._lock:
            out = json.loads(json.dumps(self.stats))
            out["acked"] = len(self.ledger)
        return out


def verify_ledger(harness: ClusterHarness, ledger: list[dict],
                  nodes=None, timeout_per_file: float = 60.0,
                  download=None) -> dict:
    """THE acked-write invariant, in ONE place for every generator:
    each ledger entry must download byte-identical (status 200, exact
    size, sha256(body) == fileId) from a live node, with one retry on
    a different node before declaring loss — readable from the
    CLUSTER, not from the first node asked. ``download(entry, node) ->
    bool`` overrides the check (the threaded LoadGen counts its
    verification reads into its own stats); the default is a
    stats-neutral direct probe."""
    node_list = list(nodes or range(1, harness.n + 1))

    def direct(entry: dict, node: int) -> bool:
        try:
            status, body = harness.http(
                node, "GET", f"/download?fileId={entry['fileId']}",
                timeout=timeout_per_file)
        except OSError:
            return False
        return (status == 200 and len(body) == entry["size"]
                and _sha256_hex(body) == entry["fileId"])

    check = download if download is not None else direct
    lost: list[str] = []
    for i, entry in enumerate(ledger):
        if not (check(entry, node_list[i % len(node_list)])
                or check(entry, node_list[(i + 1) % len(node_list)])):
            lost.append(entry["fileId"])
    return {"checked": len(ledger),
            "ok": len(ledger) - len(lost), "lost": lost}


# ------------------------------------------------------------------ #
# multi-process open-loop overload generator (docs/chaos.md §overload)
# ------------------------------------------------------------------ #

def _decorrelated_503_sleep(rng, retry_after_s: float,
                            prev_s: float | None,
                            cap_s: float = 10.0) -> float:
    """THE Retry-After jitter rule, shared by the threaded LoadGen and
    the open-loop worker processes: at least the advertised budget, at
    most 3x the previous sleep (Brooker, "Exponential Backoff And
    Jitter"), capped — an immediate retry would re-arrive exactly with
    every other shed client."""
    base = max(0.0, retry_after_s)
    hi = 3.0 * (prev_s if prev_s is not None else base)
    return min(cap_s, rng.uniform(base, max(base, hi)))


async def _aio_http(port: int, method: str, path: str,
                    body: bytes | None = None,
                    headers: dict | None = None,
                    timeout: float = 60.0) -> tuple[int, bytes, dict]:
    """Minimal asyncio HTTP/1.1 client for the open-loop worker: one
    connection per request (the node answers ``Connection: close``),
    thousands may be in flight as coroutines — the thread-per-op
    LoadGen topped out orders of magnitude below genuine overload."""
    import asyncio

    async def go() -> tuple[int, bytes, dict]:
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        try:
            head = [f"{method} {path} HTTP/1.1",
                    "Host: 127.0.0.1", "Connection: close"]
            for k, v in (headers or {}).items():
                head.append(f"{k}: {v}")
            if body is not None:
                head.append(f"Content-Length: {len(body)}")
            writer.write(("\r\n".join(head) + "\r\n\r\n").encode())
            if body:
                writer.write(body)
            await writer.drain()
            status_line = await reader.readline()
            parts = status_line.split()
            if len(parts) < 2:
                raise ConnectionResetError("bad status line")
            status = int(parts[1])
            hdrs: dict[str, str] = {}
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                if b":" in line:
                    k, v = line.split(b":", 1)
                    hdrs[k.strip().lower().decode("latin-1")] = \
                        v.strip().decode("latin-1")
            cl = hdrs.get("content-length")
            data = await reader.readexactly(int(cl)) if cl \
                else await reader.read(-1)
            return status, data, hdrs
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    return await asyncio.wait_for(go(), timeout)


def _worker_payload(payload_bytes: int, tenant: int, seq: int) -> bytes:
    import numpy as np

    rng = np.random.default_rng((tenant << 32) ^ seq ^ 0xC4A05)
    return rng.integers(0, 256, size=payload_bytes,
                        dtype=np.uint8).tobytes()


async def _load_worker(spec: dict) -> dict:
    """One open-loop worker process: ops are SCHEDULED at the offered
    rate regardless of completions (the open-loop contract — a
    closed-loop generator throttles itself exactly when the system
    degrades, hiding the overload this exists to provoke), each op a
    coroutine, thousands concurrently in flight. 503s are retried
    after the advertised Retry-After with decorrelated jitter. Returns
    {stats, ledger, latencies} — the parent aggregates across workers."""
    import asyncio
    import random as _random

    rng = _random.Random(spec["seed"])
    rate = float(spec["rate_per_s"])
    interval = 1.0 / rate
    payload_bytes = int(spec["payload_bytes"])
    tenants = int(spec["tenants"])
    upload_fraction = float(spec["upload_fraction"])
    ports = {int(k): int(v) for k, v in spec["ports"].items()}
    up_nodes = [int(n) for n in spec["upload_nodes"]]
    down_nodes = [int(n) for n in spec["download_nodes"]]
    op_timeout = float(spec["op_timeout_s"])
    deadline_s = spec.get("deadline_s")
    retry_503 = int(spec.get("retry_503", 2))
    max_inflight = int(spec.get("max_inflight", 2000))
    worker_id = int(spec.get("worker_id", 0))

    stats = {"uploads_attempted": 0, "uploads_acked": 0,
             "uploads_failed": 0, "ack_hash_mismatch": 0,
             "downloads_attempted": 0, "downloads_ok": 0,
             "downloads_failed": 0, "download_mismatch": 0,
             "retries_503": 0, "transport_errors": 0,
             "overflow_dropped": 0, "abandoned": 0,
             "inflight_peak": 0, "status": {}}
    ledger: list[dict] = []
    # latency of the SUCCESSFUL attempt only (per-attempt clock reset):
    # the goodput-SLO gate judges what ADMITTED requests experienced —
    # shed-and-retried time is the client's backoff, not server goodput
    latencies: dict[str, list[float]] = {"upload": [], "download": []}

    def count_status(status) -> None:
        key = str(status)
        stats["status"][key] = stats["status"].get(key, 0) + 1

    async def request(node: int, method: str, path: str,
                      body: bytes | None = None) -> tuple[int, bytes,
                                                          float]:
        """-> (status, body, last_attempt_seconds); honors Retry-After
        on 503 with decorrelated jitter. OSError-class on transport
        failure, like the threaded LoadGen."""
        headers = {}
        if deadline_s is not None:
            headers["X-Dfs-Deadline"] = f"{deadline_s:g}"
        prev: float | None = None
        for attempt in range(1 + retry_503):
            t0 = time.monotonic()
            status, data, hdrs = await _aio_http(
                ports[node], method, path, body=body, headers=headers,
                timeout=op_timeout)
            took = time.monotonic() - t0
            if status != 503 or attempt >= retry_503:
                return status, data, took
            count_status(503)
            try:
                ra = float(hdrs.get("retry-after", 1.0))
            except ValueError:
                ra = 1.0
            stats["retries_503"] += 1
            prev = _decorrelated_503_sleep(rng, ra, prev)
            await asyncio.sleep(prev)
        return status, data, took

    async def upload_once(tenant: int, seq: int, node: int) -> None:
        data = _worker_payload(payload_bytes, tenant, seq)
        want = _sha256_hex(data)
        stats["uploads_attempted"] += 1
        try:
            status, body, took = await request(
                node, "POST", f"/upload?name=t{tenant}%2Ff{seq}.bin",
                body=data)
        except (OSError, asyncio.TimeoutError, EOFError,
                asyncio.IncompleteReadError):
            stats["uploads_failed"] += 1
            stats["transport_errors"] += 1
            return
        count_status(status)
        if status != 201:
            stats["uploads_failed"] += 1
            return
        info = json.loads(body)
        if info.get("fileId") != want:
            stats["ack_hash_mismatch"] += 1
            return
        stats["uploads_acked"] += 1
        ledger.append({"fileId": want, "size": len(data),
                       "node": node, "tenant": tenant})
        latencies["upload"].append(took)

    async def download_once(entry: dict, node: int) -> None:
        stats["downloads_attempted"] += 1
        try:
            status, body, took = await request(
                node, "GET", f"/download?fileId={entry['fileId']}")
        except (OSError, asyncio.TimeoutError, EOFError,
                asyncio.IncompleteReadError):
            stats["downloads_failed"] += 1
            stats["transport_errors"] += 1
            return
        count_status(status)
        if status != 200:
            stats["downloads_failed"] += 1
            return
        if len(body) != entry["size"] \
                or _sha256_hex(body) != entry["fileId"]:
            stats["download_mismatch"] += 1
            return
        stats["downloads_ok"] += 1
        latencies["download"].append(took)

    def pick_zipf() -> dict | None:
        n = len(ledger)
        if n == 0:
            return None
        # rank 1 = newest; p(rank) ∝ 1/rank^1.2 — the LoadGen mix, but
        # sampled in O(1) via the continuous Pareto inverse (the
        # threaded LoadGen builds an O(acked) weight table per op,
        # which an open loop firing thousands of ops/s cannot afford)
        rank = min(n, int(rng.paretovariate(0.2)))
        return ledger[n - max(1, rank)]

    inflight: set = set()
    seq = worker_id << 24   # distinct payload/tenant space per worker

    async def one_op() -> None:
        nonlocal seq
        if rng.random() < upload_fraction or not ledger:
            seq += 1
            await upload_once(rng.randrange(tenants) + worker_id * 1000,
                              seq, rng.choice(up_nodes))
        else:
            entry = pick_zipf()
            if entry is not None:
                await download_once(entry, rng.choice(down_nodes))

    loop_end = time.monotonic() + float(spec["seconds"])
    next_fire = time.monotonic()
    while time.monotonic() < loop_end:
        # offered-rate pacing: the next op fires on the SCHEDULE, not
        # on completions — in-flight count grows with server latency
        if len(inflight) >= max_inflight:
            stats["overflow_dropped"] += 1   # honest accounting: an
            # offered op the bounded generator could not carry
        else:
            t = asyncio.ensure_future(one_op())
            inflight.add(t)
            t.add_done_callback(inflight.discard)
            stats["inflight_peak"] = max(stats["inflight_peak"],
                                         len(inflight))
        # behind schedule: fire the NEXT op immediately but never
        # "catch up" by bursting the backlog — a 2 s loop stall at
        # 500 ops/s would otherwise discharge ~1000 ops in one tick,
        # a synthetic thundering herd the offered-rate contract (and
        # the shed/latency artifacts gated on it) must not contain
        next_fire = max(next_fire + interval, time.monotonic())
        delay = next_fire - time.monotonic()
        if delay > 0:
            await asyncio.sleep(delay)

    drain_end = time.monotonic() + float(spec.get("drain_s", 30.0))
    while inflight and time.monotonic() < drain_end:
        await asyncio.wait(set(inflight), timeout=1.0)
    for t in list(inflight):
        t.cancel()
        stats["abandoned"] += 1
    if inflight:
        await asyncio.gather(*inflight, return_exceptions=True)
    latencies["upload"].sort()
    latencies["download"].sort()
    # bounded artifact: the percentile math needs the sorted sample,
    # not every point — cap what crosses the process boundary
    cap = 20000
    return {"stats": stats, "ledger": ledger,
            "latencies": {k: v[:: max(1, len(v) // cap)]
                          for k, v in latencies.items()}}


def load_worker_main(spec_path: str) -> int:
    """CLI entry for one worker process:
    ``python -m scripts.chaos_harness --load-worker <spec.json>``."""
    import asyncio

    spec = json.loads(Path(spec_path).read_text())
    result = asyncio.run(_load_worker(spec))
    Path(spec["out"]).write_text(json.dumps(result))
    return 0


def percentile(sorted_xs: list[float], q: float) -> float:
    """Nearest-rank percentile of an already-sorted sample (0 when
    empty — callers gate on sample size first)."""
    if not sorted_xs:
        return 0.0
    i = min(len(sorted_xs) - 1, max(0, int(q * len(sorted_xs))))
    return sorted_xs[i]


class ProcLoadGen:
    """Multi-PROCESS open-loop load: K worker processes, each an
    asyncio open loop firing ops at ``rate_per_s / K`` with thousands
    of in-flight simulated tenants, paced by OFFERED RATE, never by
    completions. This is what drives genuine overload: the threaded
    LoadGen's thread-per-op model exhausts a small host's threads right
    when the system slows down — exactly when offered load must keep
    coming. Same ack-ledger/byte-identity doctrine as LoadGen; the
    parent aggregates worker ledgers and runs verify_all itself."""

    def __init__(self, harness: ClusterHarness, payload_bytes: int,
                 rate_per_s: float, procs: int = 3, tenants: int = 64,
                 upload_fraction: float = 0.5, seed: int = 77,
                 upload_nodes=None, download_nodes=None,
                 op_timeout_s: float = 30.0,
                 deadline_s: float | None = None, retry_503: int = 2,
                 max_inflight: int = 2000,
                 workdir: Path | None = None) -> None:
        self.h = harness
        self.procs = max(1, int(procs))
        self.spec = {
            "payload_bytes": payload_bytes,
            "rate_per_s": rate_per_s / self.procs,
            "tenants": tenants, "upload_fraction": upload_fraction,
            "ports": {i: harness.http_port(i)
                      for i in range(1, harness.n + 1)},
            "upload_nodes": list(upload_nodes
                                 or range(1, harness.n + 1)),
            "download_nodes": list(download_nodes
                                   or range(1, harness.n + 1)),
            "op_timeout_s": op_timeout_s, "deadline_s": deadline_s,
            "retry_503": retry_503, "max_inflight": max_inflight,
        }
        self.seed = seed
        self.workdir = Path(workdir or harness.workdir) / "loadgen"
        self.workdir.mkdir(parents=True, exist_ok=True)
        self.stats: dict = {}
        self.ledger: list[dict] = []
        self.latencies: dict[str, list[float]] = {"upload": [],
                                                  "download": []}

    def run_for(self, seconds: float, drain_s: float = 30.0) -> dict:
        """Run the fleet for ``seconds`` of offered load (plus drain),
        blocking; aggregates worker results into self.stats/ledger/
        latencies and returns the merged stats."""
        procs: list[tuple[subprocess.Popen, Path]] = []
        for w in range(self.procs):
            spec = dict(self.spec)
            spec.update(seconds=seconds, drain_s=drain_s,
                        seed=self.seed + 1000 * w, worker_id=w,
                        out=str(self.workdir / f"worker{w}.out.json"))
            spec_path = self.workdir / f"worker{w}.spec.json"
            spec_path.write_text(json.dumps(spec))
            log = (self.workdir / f"worker{w}.log").open("ab")
            procs.append((subprocess.Popen(
                [sys.executable, "-m", "scripts.chaos_harness",
                 "--load-worker", str(spec_path)],
                cwd=REPO, env={**os.environ, "PYTHONPATH": str(REPO)},
                stdout=log, stderr=subprocess.STDOUT), Path(spec["out"])))
        merged: dict = {"status": {}}
        deadline_t = time.time() + seconds + drain_s + 60.0
        for w, (p, out_path) in enumerate(procs):
            try:
                p.wait(timeout=max(5.0, deadline_t - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
            if not out_path.is_file():
                raise HarnessError(
                    f"load worker {w} died without a result: "
                    + (self.workdir / f"worker{w}.log").read_text(
                        errors="replace")[-2000:])
            res = json.loads(out_path.read_text())
            for k, v in res["stats"].items():
                if k == "status":
                    for s, n in v.items():
                        merged["status"][s] = \
                            merged["status"].get(s, 0) + n
                elif k == "inflight_peak":
                    merged[k] = max(merged.get(k, 0), v)
                else:
                    merged[k] = merged.get(k, 0) + v
            self.ledger.extend(res["ledger"])
            for k in self.latencies:
                self.latencies[k].extend(res["latencies"].get(k, []))
        for k in self.latencies:
            self.latencies[k].sort()
        merged["acked"] = len(self.ledger)
        self.stats = merged
        return merged

    def latency_percentiles(self, kind: str) -> dict:
        xs = self.latencies.get(kind, [])
        return {"n": len(xs),
                "p50": round(percentile(xs, 0.50), 4),
                "p95": round(percentile(xs, 0.95), 4),
                "p99": round(percentile(xs, 0.99), 4)}

    def verify_all(self, nodes=None) -> dict:
        """THE invariant, the one :func:`verify_ledger` rule: every
        acked upload downloads byte-identical from a live node (one
        retry on a second node)."""
        return verify_ledger(self.h, self.ledger, nodes=nodes,
                             timeout_per_file=120.0)


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--load-worker":
        sys.exit(load_worker_main(sys.argv[2]))
    print("usage: python -m scripts.chaos_harness --load-worker "
          "<spec.json>", file=sys.stderr)
    sys.exit(2)
