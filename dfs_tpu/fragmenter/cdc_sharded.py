"""Multi-device streaming CDC — the ingest option behind
``FragmenterConfig.devices`` (round 10, ROADMAP item 5b).

``CpuCdcFragmenter`` with ONE substitution: the streaming bitmap kernel
(the pluggable ``bitmap_fn`` seam ``fragmenter/stream.py`` was built
around) runs regions through ``parallel/sharded_cdc.
make_sharded_bitmap_step`` — the windowed Gear bitmap computed as one
SPMD program over a ('dp','sp') mesh, the 31-byte window halo exchanged
between sp-ring neighbors via ``lax.ppermute`` and the stream's
region-to-region halo carried in as an explicit input. Everything else
(greedy cut selection, hashing, manifests, the resume ``describe()``)
is inherited unchanged, so chunk boundaries and digests are
BYTE-IDENTICAL to the single-device path by construction —
tests/test_sharded_ingest.py asserts it against the CPU oracle, and
WIRE_r10.json carries the resident multi-device throughput claim.

Streaming input is re-blocked to a FIXED region size
(``FragmenterConfig.region_bytes``, default ``devices`` MiB) so the
sharded step traces/compiles exactly once; the stream's ragged final
region falls back to the NumPy kernel (identical bitmap, no recompile).
Fewer visible JAX devices than configured logs once and runs the CPU
path — a degraded environment must not fail ingest.
"""

from __future__ import annotations

import numpy as np

from dfs_tpu.config import GEAR_HALO as HALO
from dfs_tpu.config import CDCParams, FragmenterConfig
from dfs_tpu.fragmenter.cdc_cpu import CpuCdcFragmenter
from dfs_tpu.fragmenter.sharded_common import (ShardedSteps,
                                               fixed_region_bytes)
from dfs_tpu.meta.manifest import Manifest


class ShardedCdcFragmenter(CpuCdcFragmenter):
    """CpuCdcFragmenter whose streaming bitmap is sharded over JAX
    devices. Same ``name``/``describe()`` as the CPU engine — manifests
    record the *strategy*, and the strategy's output is identical."""

    def __init__(self, params: CDCParams | None = None,
                 frag: FragmenterConfig | None = None) -> None:
        super().__init__(params)
        frag = frag or FragmenterConfig(devices=2)
        self.devices = max(2, int(frag.devices))
        # compile-shape policy (sharded_common): per-device spans must be
        # equal (static shapes) and long enough to source the 31-value
        # ring halo from their own tile -> granule = devices bytes,
        # floor = devices * 4 * HALO
        self.region_bytes = max(
            self.devices * 4 * HALO,
            fixed_region_bytes(frag.region_bytes,
                               self.devices * (1 << 20), self.devices))
        self._steps = ShardedSteps(self.devices, self._build)

    # ---- device plumbing ----

    def _build(self, mesh):
        from dfs_tpu.parallel.sharded_cdc import make_sharded_bitmap_step

        return make_sharded_bitmap_step(mesh, self.table, self.params.mask)

    @property
    def _unavailable(self) -> bool:
        """Degraded-environment flag (tests pin it) — the single
        fallback predicate lives in sharded_common.ShardedSteps."""
        return self._steps.unavailable

    def _ensure_step(self):
        return self._steps.get()

    # ---- the substituted kernel ----

    def bitmap_tile(self, arr: np.ndarray,
                    prev_g: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        step = self._ensure_step()
        if step is None or arr.shape[0] != self.region_bytes:
            # ragged final region / degraded environment: the NumPy
            # kernel computes the SAME bitmap (single source of truth
            # for halos — gear_bitmap_carry), no device recompile
            return super().bitmap_tile(arr, prev_g)
        import jax

        from dfs_tpu.parallel.sharded_cdc import shard_bitmap_inputs

        data, head = shard_bitmap_inputs(
            self._steps.mesh, np.ascontiguousarray(arr)[None, :],
            np.ascontiguousarray(prev_g)[None, :])
        bitmap = np.asarray(jax.block_until_ready(step(data, head)))[0]
        # next region's carry halo: Gear table values of the last
        # 31 bytes (region_bytes >> HALO, so no prev_g splice needed)
        return bitmap, self.table[arr[-HALO:]].astype(np.uint32)

    def manifest_stream(self, blocks, name: str, store=None) -> Manifest:
        from dfs_tpu.fragmenter.stream import manifest_from_stream, reblock

        # fixed-size regions -> ONE compiled step shape for the whole
        # stream (only the final ragged region takes the NumPy path)
        return manifest_from_stream(
            reblock(blocks, self.region_bytes), self.params,
            self.bitmap_tile, name, self.name, store)
