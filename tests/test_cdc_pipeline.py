"""Fused aligned-CDC pipeline + fragmenters vs the NumPy oracle.

Mirrors the reference's only self-checks (replication hash echo
StorageNode.java:248-257, download hash-vs-id :453-458) as properties:
device spans/digests == oracle == hashlib, streaming == one-shot, and the
manifest machinery round-trips.
"""

import hashlib

import numpy as np
import pytest

from dfs_tpu.fragmenter.base import get_fragmenter
from dfs_tpu.fragmenter.cdc_aligned import (AlignedCpuFragmenter,
                                            AlignedTpuFragmenter)
from dfs_tpu.ops.cdc_pipeline import cut_capacity, segment_chunks
from dfs_tpu.ops.cdc_v2 import (AlignedCdcParams, chunk_file_np,
                                file_id_from_digests)

SMALL = AlignedCdcParams(min_blocks=2, avg_blocks=4, max_blocks=16,
                         strip_blocks=64)  # 4 KiB strips for fast tests


def corpus(n, seed=0):
    return np.random.default_rng(seed).integers(0, 256, size=n, dtype=np.uint8)


@pytest.mark.parametrize("n", [1, 63, 64, 65, 4096, 4097, 40000, 300001])
def test_segment_matches_oracle(n):
    data = corpus(n, seed=n)
    got = segment_chunks(data, SMALL, lane_multiple=8)
    want = chunk_file_np(data, SMALL)
    assert got == want


def test_segment_digests_are_sha256():
    data = corpus(50000, seed=2)
    for o, ln, dg in segment_chunks(data, SMALL, lane_multiple=8):
        assert dg == hashlib.sha256(data[o:o + ln].tobytes()).hexdigest()


def test_segment_low_entropy_and_sparse_candidates():
    # all-zeros: no candidates -> max-size chunks everywhere (forced cuts)
    data = np.zeros((100000,), dtype=np.uint8)
    got = segment_chunks(data, SMALL, lane_multiple=8)
    assert got == chunk_file_np(data, SMALL)
    for _, ln, _ in got[:-1]:
        assert ln <= SMALL.max_blocks * 64


def test_cut_capacity_bounds_real_cut_count():
    data = corpus(300000, seed=5)
    got = segment_chunks(data, SMALL, lane_multiple=8)
    s = -(-data.shape[0] // SMALL.strip_len)
    assert len(got) <= cut_capacity(s, SMALL)


# ------------------------------------------------------------ fragmenters --

def tpu_frag(**kw):
    return AlignedTpuFragmenter(SMALL, cpu_cutoff=0, lane_multiple=8, **kw)


def test_fragmenters_agree_and_cover():
    data = corpus(200000, seed=7).tobytes()
    cpu = AlignedCpuFragmenter(SMALL).chunk(data)
    tpu = tpu_frag().chunk(data)
    assert cpu == tpu
    assert sum(c.length for c in cpu) == len(data)


def test_segment_loop_is_transparent():
    # seg_strips=2 forces the multi-segment path; strips restart chunking,
    # so segment boundaries must not change the result
    data = corpus(SMALL.strip_len * 5 + 321, seed=8).tobytes()
    assert tpu_frag(seg_strips=2).chunk(data) == tpu_frag().chunk(data)


def test_manifest_and_stream_match():
    data = corpus(150000, seed=9).tobytes()
    frag = tpu_frag(seg_strips=2)
    m1 = frag.manifest(data, name="f")
    stored: dict[str, bytes] = {}
    blocks = [data[i:i + 7000] for i in range(0, len(data), 7000)]
    m2 = frag.manifest_stream(blocks, name="f",
                              store=lambda dg, b: stored.setdefault(dg, b))
    assert m1.file_id == m2.file_id == file_id_from_digests(m1.digests())
    assert m1.chunks == m2.chunks
    # stored payloads reassemble the stream byte-identically
    assert b"".join(stored[c.digest] for c in m2.chunks) == data
    for dg, b in stored.items():
        assert hashlib.sha256(b).hexdigest() == dg


def test_empty_and_tiny():
    assert tpu_frag().chunk(b"") == []
    m = tpu_frag().manifest(b"x", name="t")
    assert m.size == 1 and len(m.chunks) == 1
    assert m.chunks[0].digest == hashlib.sha256(b"x").hexdigest()


def test_factory_kinds():
    assert get_fragmenter("cdc-aligned").name == "cdc-aligned"
    assert get_fragmenter("cdc-aligned-tpu").name == "cdc-aligned-tpu"


def test_factory_byte_params_conversion():
    from dfs_tpu.config import CDCParams

    f = get_fragmenter("cdc-aligned", cdc_params=CDCParams(
        min_size=1024, avg_size=4096, max_size=32768))
    assert (f.params.min_blocks, f.params.avg_blocks,
            f.params.max_blocks) == (16, 64, 512)
    # --max-chunk beyond the default strip grows the strip (CLI values that
    # are legal for cdc/cdc-tpu must not crash node startup)
    big = get_fragmenter("cdc-aligned", cdc_params=CDCParams(
        min_size=2048, avg_size=8192, max_size=256 * 1024))
    assert big.params.max_blocks == 4096
    assert big.params.strip_blocks >= big.params.max_blocks


def test_streaming_honors_seg_strips():
    frag = tpu_frag(seg_strips=2)
    data = corpus(SMALL.strip_len * 5, seed=11).tobytes()
    segs = list(frag._segments([data]))
    assert [s.shape[0] for s in segs] == [SMALL.strip_len * 2,
                                          SMALL.strip_len * 2,
                                          SMALL.strip_len]
