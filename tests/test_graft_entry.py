"""Driver contract: entry() compiles single-device; dryrun_multichip executes
the sharded step on the virtual 8-device mesh (it self-checks vs oracles)."""

import sys
from pathlib import Path

import jax
import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import __graft_entry__  # noqa: E402


def test_entry_compiles_and_runs():
    fn, args = __graft_entry__.entry()
    jitted = jax.jit(fn)
    bitmap, tail, state = jitted(*args)
    assert bitmap.shape == (args[0].shape[0],)
    assert tail.shape == (31,)
    assert state.shape == (args[3].shape[0], 8)
    # digest rows must match hashlib for the example messages
    import hashlib
    from dfs_tpu.ops.sha256_jax import state_to_hex
    # recover the example messages deterministically (same seed as entry())
    rng = np.random.default_rng(0)
    rng.integers(0, 256, size=64 * 1024, dtype=np.uint8)  # skip data draw
    lens = rng.integers(1, 2048, size=32)
    msgs = [rng.integers(0, 256, size=int(ln), dtype=np.uint8).tobytes()
            for ln in lens]
    assert state_to_hex(np.asarray(state)) == [
        hashlib.sha256(m).hexdigest() for m in msgs]


def test_dryrun_multichip_8():
    __graft_entry__.dryrun_multichip(8)


def test_dryrun_multichip_4():
    __graft_entry__.dryrun_multichip(4)
