"""Bounded readahead for streamed downloads.

``download_stream`` fetches chunk batches strictly one at a time: while a
batch's bytes drain to the client socket, the storage plane sits idle,
and while the next batch fetches, the socket sits idle — the two costs
serialize. :class:`BatchPrefetcher` overlaps them: up to ``ahead``
batches beyond the one being consumed are fetched eagerly (as asyncio
tasks), so by the time the writer wants batch *i+1* its bytes are
usually already verified and (when the serving tier's cache is on)
already hot for the next reader of the same file.

Memory stays bounded by construction: at most ``ahead + 1`` batch
results exist at once (a result is dropped as soon as it is handed
over), exactly the contract the non-prefetching path keeps at 1.

Failure order is preserved: a prefetched batch's exception surfaces when
the consumer reaches THAT batch, never earlier — the stream truncates at
the same byte it would have without readahead. ``close()`` cancels
whatever is still in flight (client disconnect mid-download)."""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Sequence


class BatchPrefetcher:
    def __init__(self, batches: Sequence,
                 fetch: Callable[[object], Awaitable],
                 ahead: int, start: int = 0) -> None:
        """``start``: first batch index this prefetcher owns — the
        streamed-download path fetches batch 0 eagerly OUTSIDE the
        prefetcher (failures must surface before the response head, and
        an unstarted body generator must own no in-flight tasks)."""
        self._batches = batches
        self._fetch = fetch
        self._ahead = max(0, int(ahead))
        self._tasks: dict[int, asyncio.Task] = {}
        self._next = max(0, int(start))   # first index not yet scheduled

    @staticmethod
    def _retrieve(task: asyncio.Task) -> None:
        # mark a failed prefetch's exception retrieved: the consumer may
        # abandon the stream before reaching the failing batch, and the
        # loop would otherwise log "exception was never retrieved" at GC
        if not task.cancelled():
            task.exception()

    def _schedule_through(self, upto: int) -> None:
        upto = min(upto, len(self._batches) - 1)
        while self._next <= upto:
            i = self._next
            t = asyncio.create_task(self._fetch(self._batches[i]))
            t.add_done_callback(self._retrieve)
            self._tasks[i] = t
            self._next += 1

    def prime(self) -> None:
        """Start the initial readahead window without awaiting anything
        — called once the consumer is committed to draining the stream
        (batches ``start`` .. ``start + ahead - 1`` begin fetching while
        the batch before ``start`` drains)."""
        self._schedule_through(self._next + self._ahead - 1)

    async def get(self, i: int):
        """Result for batch ``i`` (consumed in order by the stream
        writer); schedules readahead through ``i + ahead``."""
        self._schedule_through(i + self._ahead)
        task = self._tasks.pop(i)
        return await task

    async def close(self) -> None:
        """Cancel outstanding fetches (consumer abandoned the stream)."""
        tasks = list(self._tasks.values())
        self._tasks.clear()
        for t in tasks:
            t.cancel()
        for t in tasks:
            try:
                await t
            # teardown: the consumer abandoned the stream, so a fetch
            # failure has no one left to tell — deliberately silent
            except (asyncio.CancelledError, Exception):  # noqa: BLE001  # dfslint: ignore[DFS007]
                pass
