"""Deterministic, seed-driven fault injection (docs/chaos.md).

The diagnosis and census planes (r11/r12) can *observe* a sick cluster;
this package exists to *provoke* one on demand, so the durability
invariants — no acked write is ever lost, reads stay byte-identical
through every failure — can be asserted under faults instead of assumed
(ROADMAP item 4). Three fault families, each threaded through an
existing seam:

- **Peer faults** — outbound latency, dropped connections, one-way
  partitions, mid-frame byte truncation — injected in the RPC client
  (:meth:`dfs_tpu.comm.rpc.InternalClient._call_once`) and, for
  whole-node slowness, in the inbound frame server
  (``runtime._serve_internal_frame``).
- **Disk faults** — ENOSPC, EIO, slow I/O — injected via the
  :class:`~dfs_tpu.store.cas.ChunkStore` fault hook, which runs on the
  bounded CAS worker threads (never the event loop) and therefore
  covers :class:`~dfs_tpu.store.aio.AsyncChunkStore` too.
- **Crash points** — ``kill -9``-grade process death at named points in
  the write path (:data:`CRASH_POINTS`), e.g. "after CAS put, before
  manifest" — the exact windows fsync-before-ack durability
  (store/cas.py, DurabilityConfig) exists to survive.

Discipline:

- **Default-off, zero overhead.** A node built from ``ChaosConfig()``
  holds NO injector (``runtime.chaos is None``); every seam is one
  ``is None`` branch. tests/test_chaos.py asserts the disabled node is
  byte-identical to r12 behavior.
- **Deterministic.** Every probabilistic decision draws from one
  ``random.Random(seed ^ node_id)`` stream in call order — the same
  seed and call sequence produce the same fault schedule (unit-tested).
- **Journaled.** Every injected fault emits a trace-stamped
  ``chaos_inject`` journal event and bumps a per-kind counter
  (``/metrics`` ``chaos`` section), so a harness assertion failure can
  be walked back to exactly which faults fired inside which requests.
- **Runtime-scriptable.** ``POST /chaos`` (api/http.py) swaps the
  active knobs atomically — the cluster harness
  (scripts/chaos_harness.py) scripts inject → observe → heal scenarios
  against live nodes; the master ``enabled`` switch itself is boot-only.
"""

from __future__ import annotations

import errno
import os
import random
import signal
import threading
import time

from dfs_tpu.config import ChaosConfig

# Registered crash points: the named moments in the write/demotion
# paths where a configured injector kills the process with SIGKILL
# (kill -9 grade — no finally blocks, no flushes; exactly what
# fsync-before-ack must survive). bench_chaos.py and tests/test_chaos.py
# iterate this registry, so a new crash site must be added HERE to be
# exercised; ``place.*``/``upload.*`` points fire on a default-config
# upload, ``demote.*`` points fire only during a tiering demotion
# (exercised by tests/test_tiering.py).
CRASH_POINTS = frozenset({
    # _place_batch: before any local CAS put of the batch
    "place.before_local_put",
    # _place_batch: local puts + replication done, before quorum check
    "place.after_replicate",
    # _finalize_upload: chunks durable, manifest NOT yet written — the
    # classic "after CAS put, before manifest" torn-upload window
    "upload.before_manifest",
    # _finalize_upload: manifest written (upload is durable), before
    # the announce fan-out / HTTP ack
    "upload.after_manifest",
    # _demote_file: parity durable at its stripe holders, the cold
    # manifest NOT yet written — the file must stay readable replicated
    "demote.after_parity_write",
    # _demote_file: cold manifest committed + index tier bit flipped,
    # surplus replicas NOT yet deleted — readable either way, surplus
    # reclaimed by the next scan's finish pass
    "demote.after_tier_flip",
    # _demote_file: immediately before the surplus-replica deletes of
    # an already-cold file — the torn window where only SOME deletes
    # landed; every remaining read must reconstruct from the stripe
    "demote.before_replica_delete",
    # similarity plane (dfs_tpu.sim) — ``sim.*`` points fire only when
    # the plane stores/serves delta chunks (exercised by bench_sim.py
    # and tests/test_sim.py, like demote.* via test_tiering.py):
    # ChunkStore delta put: delta file linked, index record NOT yet
    # written — the false-negative window the stat backstop covers
    "sim.after_delta_write",
    # NodeStore.gc: live + delta-pinned sets computed, before any
    # orphan delete — a crash mid-GC must never have deleted a base
    # whose delta dependents are live
    "sim.before_base_gc",
    # ChunkStore re-materialize-on-hot: raw copy durable, the delta
    # file NOT yet unlinked — both representations present, raw wins
    "sim.after_rematerialize",
    # BandIndex log compaction: compacted log written and fsynced at
    # its temp name, bands.log NOT yet atomically replaced — replay
    # must still serve the old complete log, and the next compaction
    # unlinks the leftover temp
    "sim.band_compact",
})

# knobs POST /chaos may change at runtime (everything except the
# master switch and the boot-time seed)
MUTABLE_KNOBS = frozenset({
    "rpc_delay_s", "rpc_delay_peers", "rpc_drop_rate", "partition",
    "rpc_truncate_rate", "serve_delay_s", "disk_error_rate",
    "disk_full", "disk_delay_s", "crash_point",
})


def _peer_set(spec: str) -> frozenset[int] | None:
    """csv of node ids -> frozenset, or None for '' (= every peer)."""
    if not spec:
        return None
    return frozenset(int(p) for p in spec.split(",") if p.strip())


class ChaosError(OSError):
    """An injected transport fault. An OSError subclass on purpose: the
    RPC retry loop treats it exactly like a real connection failure
    (retry → backoff → budget → RpcUnreachable), which is the point —
    injected faults must exercise the REAL failure paths."""


class ChunkStoreFault:
    """The :class:`ChunkStore` fault hook an injector installs: called
    at the top of every put/get ON THE CAS WORKER THREAD (so injected
    disk delays never touch the event loop). Raises the injected
    OSError or sleeps; counts every fault it fires."""

    def __init__(self, injector: "ChaosInjector") -> None:
        self._inj = injector

    def __call__(self, op: str, digest: str) -> None:
        inj = self._inj
        cfg = inj.cfg          # ONE snapshot: knobs can't mix mid-swap
        if cfg.disk_delay_s > 0:
            time.sleep(cfg.disk_delay_s)
            inj.count("disk_delay")
        if op == "put" and cfg.disk_full:
            inj.count("disk_full", digest=digest[:12])
            raise OSError(errno.ENOSPC, "chaos: injected disk full")
        if cfg.disk_error_rate > 0 \
                and inj.roll() < cfg.disk_error_rate:
            inj.count("disk_error", op=op, digest=digest[:12])
            raise OSError(errno.EIO, f"chaos: injected {op} EIO")


class ChaosInjector:
    """One node's active fault state. Thread-safe: knobs are read from
    the event loop (RPC seams) and CAS worker threads (disk hook);
    ``set()`` swaps them under a lock. The decision RNG is its own
    lock-guarded stream so decision ORDER — and therefore the fault
    schedule under a fixed seed — is well-defined."""

    def __init__(self, cfg: ChaosConfig, node_id: int, obs=None) -> None:
        if cfg.crash_point and cfg.crash_point not in CRASH_POINTS:
            raise ValueError(
                f"unknown crash point {cfg.crash_point!r} "
                f"(registered: {sorted(CRASH_POINTS)})")
        self.node_id = node_id
        self._obs = obs
        self._lock = threading.Lock()
        # seed ^ node_id, exactly as documented (config.py, docs/
        # chaos.md): an operator must be able to reproduce a node's
        # fault schedule offline from the two numbers alone
        self._rng = random.Random(cfg.seed ^ node_id)
        self._counts: dict[str, int] = {}
        self._apply(cfg)

    # ---- knob state -------------------------------------------------- #

    def _apply(self, cfg: ChaosConfig) -> None:
        # ONE reference swap carries every knob: readers (event-loop
        # RPC seams, CAS worker threads) take one snapshot of _state
        # and never observe a mix of old and new knobs mid-set() —
        # the atomicity POST /chaos advertises
        self._state = (cfg, _peer_set(cfg.rpc_delay_peers),
                       _peer_set(cfg.partition) or frozenset())

    @property
    def cfg(self) -> ChaosConfig:
        """The active knob snapshot (immutable; atomic to read)."""
        return self._state[0]

    def set(self, **knobs) -> dict:
        """Swap mutable knobs at runtime (POST /chaos). Unknown or
        immutable knob names raise ValueError — the harness must fail
        loudly on a typo, not silently run a different scenario.
        Values are validated by rebuilding the frozen ChaosConfig."""
        bad = set(knobs) - MUTABLE_KNOBS
        if bad:
            raise ValueError(f"unknown/immutable chaos knobs: "
                             f"{sorted(bad)}")
        import dataclasses

        with self._lock:
            cfg = dataclasses.replace(self.cfg, **knobs)
            if cfg.crash_point and cfg.crash_point not in CRASH_POINTS:
                raise ValueError(
                    f"unknown crash point {cfg.crash_point!r}")
            self._apply(cfg)
        if self._obs is not None:
            self._obs.event("chaos_set",
                            knobs={k: knobs[k] for k in sorted(knobs)})
        return self.stats()

    def roll(self) -> float:
        """One uniform [0,1) draw from the node's deterministic decision
        stream (decision order defines the schedule)."""
        with self._lock:
            return self._rng.random()

    def count(self, kind: str, **fields) -> None:
        """Meter + journal one injected fault (trace-stamped via the
        obs context, so `trace <id>` shows which request ate it)."""
        with self._lock:
            self._counts[kind] = self._counts.get(kind, 0) + 1
        if self._obs is not None:
            self._obs.event("chaos_inject", kind=kind, **fields)

    # ---- peer faults (RPC client seam) ------------------------------- #

    def partitioned(self, peer_id: int) -> bool:
        return peer_id in self._state[2]

    def check_partition(self, peer_id: int, op: str) -> None:
        """Raise before dialing when this node's link to the peer is
        partitioned away (one-way: only THIS side's sends fail)."""
        if peer_id in self._state[2]:
            self.count("partition", peer=peer_id, op=op)
            raise ChaosError(errno.EHOSTUNREACH,
                             f"chaos: partitioned from node {peer_id}")

    async def before_rpc(self, peer_id: int, op: str) -> None:
        """Outbound-call faults that fire before the frame is sent:
        injected link latency, then a possible connection drop."""
        import asyncio

        cfg, delay_peers, _ = self._state
        if cfg.rpc_delay_s > 0 and (delay_peers is None
                                    or peer_id in delay_peers):
            self.count("rpc_delay", peer=peer_id, op=op)
            await asyncio.sleep(cfg.rpc_delay_s)
        if cfg.rpc_drop_rate > 0 and self.roll() < cfg.rpc_drop_rate:
            self.count("rpc_drop", peer=peer_id, op=op)
            raise ChaosError(errno.ECONNRESET,
                             f"chaos: dropped call to node {peer_id}")

    def truncate_now(self, peer_id: int, op: str) -> bool:
        """Whether to truncate THIS outbound frame mid-body (the caller
        writes a torn frame and closes — the receiver's torn-frame
        handling is what gets exercised)."""
        rate = self.cfg.rpc_truncate_rate
        if rate <= 0 or self.roll() >= rate:
            return False
        self.count("rpc_truncate", peer=peer_id, op=op)
        return True

    # ---- inbound faults (frame server seam) -------------------------- #

    async def before_serve(self, op: str) -> None:
        """Inbound service delay: the whole node is slow (the shape the
        doctor's slow_peer rule diagnoses from peers' client tables)."""
        import asyncio

        delay = self.cfg.serve_delay_s
        if delay > 0:
            self.count("serve_delay", op=op)
            await asyncio.sleep(delay)

    # ---- disk faults (ChunkStore hook) ------------------------------- #

    def store_hook(self) -> ChunkStoreFault:
        return ChunkStoreFault(self)

    # ---- crash points ------------------------------------------------ #

    def maybe_crash(self, point: str) -> None:
        """Die by SIGKILL if ``point`` is the configured crash point.
        The journal event is best-effort (the bounded writer thread may
        not flush it — that is the point of kill -9); the harness
        correlates crashes by exit signal, not by journal."""
        if point != self.cfg.crash_point:
            return
        if self._obs is not None:
            self._obs.event("chaos_crash", point=point)
        os.kill(os.getpid(), signal.SIGKILL)

    # ---- surface ----------------------------------------------------- #

    def stats(self) -> dict:
        """``/metrics`` ``chaos`` section: the active knobs plus
        per-kind injected-fault counters. Knob keys mirror ChaosConfig
        fields (dfslint DFS005 checks the mapping)."""
        with self._lock:
            counts = dict(sorted(self._counts.items()))
        cfg = self.cfg
        return {"enabled": True, "seed": cfg.seed,
                "rpcDelayS": cfg.rpc_delay_s,
                "rpcDelayPeers": cfg.rpc_delay_peers,
                "rpcDropRate": cfg.rpc_drop_rate,
                "partition": cfg.partition,
                "rpcTruncateRate": cfg.rpc_truncate_rate,
                "serveDelayS": cfg.serve_delay_s,
                "diskErrorRate": cfg.disk_error_rate,
                "diskFull": cfg.disk_full,
                "diskDelayS": cfg.disk_delay_s,
                "crashPoint": cfg.crash_point,
                "injected": counts}


__all__ = ["CRASH_POINTS", "MUTABLE_KNOBS", "ChaosError",
           "ChaosInjector", "ChunkStoreFault"]
