"""Multi-device CDC pipeline: shard_map over a ('dp','sp') mesh.

This is the framework's 'training step' analogue — the full device-side
upload computation, jitted once over the mesh:

- **sp axis (sequence parallelism / long-context):** each row of the input is
  a byte stream tiled across the sp axis. The Gear window straddles tile
  borders, so each device sends its tile's last 31 Gear values to its right
  ring neighbor via ``lax.ppermute`` over ICI (SURVEY.md §5.7 — the
  ring-attention-shaped neighbor exchange, with rolling-hash state instead of
  KV blocks). Device 0 receives zeros ≡ stream start.
- **dp axis (data parallelism):** independent streams (concurrent uploads)
  ride the other mesh axis — the batch of padded chunks for SHA-256 is
  sharded over the *flattened* ('dp','sp') axes so every device hashes an
  equal slice.
- a ``psum`` over both axes reduces the global candidate count (cheap stats
  used by the node runtime for chunk-size telemetry).

Contrast with the reference: its scale-out is N JVMs exchanging Base64 JSON
over localhost HTTP (StorageNode.java:226-259); here the same byte-level work
is one SPMD program with XLA collectives on ICI.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from dfs_tpu.ops.gear_jax import HALO, WINDOW
from dfs_tpu.ops.sha256_jax import _sha256_blocks_impl


def _shard_map(f, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` across the API move: newer releases export the
    stable top-level name, older ones only ``jax.experimental``'s; the
    replication-check flag was renamed check_rep -> check_vma along the
    way (and some releases have the top-level name but the OLD flag
    spelling, so the flag is chosen by signature, not by location)."""
    import inspect

    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn
    flag = "check_vma" \
        if "check_vma" in inspect.signature(fn).parameters else "check_rep"
    return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              **{flag: check_vma})


def _rowwise_gear_bitmap(data: jax.Array, prev_g: jax.Array,
                         table: jax.Array, mask: jax.Array) -> jax.Array:
    """data: [B, S] uint8; prev_g: [B, 31] uint32 (halo per row)."""
    bsz, s = data.shape
    g = jnp.take(table, data.astype(jnp.int32), axis=0)
    gp = jnp.concatenate([prev_g, g], axis=1)  # [B, S+31]
    h = jnp.zeros((bsz, s), jnp.uint32)
    for k in range(WINDOW):
        h = h + (jax.lax.slice_in_dim(gp, HALO - k, HALO - k + s, axis=1)
                 << np.uint32(k))
    return (h & mask) == 0


def make_sharded_step(mesh: Mesh, table: np.ndarray, mask: int):
    """Build the jitted multi-device step.

    step(data [B, S] u8  — B sharded over dp, S tiled over sp,
         words [H, L, 16] u32, nblocks [H] i32 — H sharded over (dp, sp))
      -> (bitmap [B, S] bool  (same sharding as data),
          digest_state [H, 8] uint32,
          n_candidates [] int32  (global psum))
    """
    table_j = jnp.asarray(table, dtype=jnp.uint32)
    mask_j = jnp.uint32(mask)
    sp_size = mesh.shape["sp"]

    def local_step(data, words, nblocks):
        # halo exchange along the sp ring: my last 31 gear values feed my
        # right neighbor's window; the first tile rolls from h=0 (zeros).
        g_tail = jnp.take(table_j, data[:, -HALO:].astype(jnp.int32), axis=0)
        prev_g = jax.lax.ppermute(
            g_tail, "sp", [(i, i + 1) for i in range(sp_size - 1)])
        bitmap = _rowwise_gear_bitmap(data, prev_g, table_j, mask_j)
        state = _sha256_blocks_impl(words, nblocks)
        n_cand = jax.lax.psum(
            jax.lax.psum(jnp.sum(bitmap.astype(jnp.int32)), "sp"), "dp")
        return bitmap, state, n_cand

    shard_fn = _shard_map(
        local_step, mesh=mesh,
        in_specs=(P("dp", "sp"), P(("dp", "sp")), P(("dp", "sp"))),
        out_specs=(P("dp", "sp"), P(("dp", "sp")), P()),
        check_vma=False,
    )
    return jax.jit(shard_fn)


def make_sharded_bitmap_step(mesh: Mesh, table: np.ndarray, mask: int):
    """Carry-in Gear bitmap over the mesh — the INGEST-side sharded step
    (round 10): ``fragmenter/cdc_sharded.py`` plugs it into the streaming
    chunker as a ``bitmap_fn``, so ``stream.py`` feeds whole regions
    through the mesh while greedy cut selection stays host-side — chunk
    boundaries are byte-identical to the single-device path by
    construction (the same bitmap, computed sharded).

    Differs from :func:`make_sharded_step`'s bitmap in one way: the
    stream's region-to-region 31-value halo enters as an explicit input
    (``head``) consumed by the FIRST sp tile instead of zeros, so
    consecutive regions of one stream chunk exactly like one long
    buffer (zeros ≡ stream start, the old behavior).

    step(data [B, S] u8 — B over dp, S tiled over sp,
         head [B, HALO] u32 — per-row carry halo, replicated over sp)
      -> bitmap [B, S] bool (same sharding as data)
    """
    table_j = jnp.asarray(table, dtype=jnp.uint32)
    mask_j = jnp.uint32(mask)
    sp_size = mesh.shape["sp"]

    def local_step(data, head):
        g_tail = jnp.take(table_j, data[:, -HALO:].astype(jnp.int32),
                          axis=0)
        prev_g = jax.lax.ppermute(
            g_tail, "sp", [(i, i + 1) for i in range(sp_size - 1)])
        # sp-rank 0's halo is the carry from the previous REGION of the
        # stream, not the ring (which handed it nothing)
        prev_g = jnp.where(jax.lax.axis_index("sp") == 0, head, prev_g)
        return _rowwise_gear_bitmap(data, prev_g, table_j, mask_j)

    shard_fn = _shard_map(
        local_step, mesh=mesh,
        in_specs=(P("dp", "sp"), P("dp", None)),
        out_specs=P("dp", "sp"),
        check_vma=False,
    )
    return jax.jit(shard_fn)


def shard_bitmap_inputs(mesh: Mesh, data: np.ndarray, head: np.ndarray):
    """device_put the carry-bitmap step inputs with matching shardings."""
    return (
        jax.device_put(data, NamedSharding(mesh, P("dp", "sp"))),
        jax.device_put(head, NamedSharding(mesh, P("dp", None))),
    )


def shard_inputs(mesh: Mesh, data: np.ndarray, words: np.ndarray,
                 nblocks: np.ndarray):
    """device_put the step inputs with the matching NamedShardings."""
    return (
        jax.device_put(data, NamedSharding(mesh, P("dp", "sp"))),
        jax.device_put(words, NamedSharding(mesh, P(("dp", "sp")))),
        jax.device_put(nblocks, NamedSharding(mesh, P(("dp", "sp")))),
    )


def make_aligned_step(mesh: Mesh, params):
    """Multi-device **aligned CDC v2** step (the flagship pipeline,
    dfs_tpu.ops.cdc_pipeline, sharded).

    Strips chunk independently (ops.cdc_v2: chunking restarts at strip
    boundaries), so the strip axis shards over the whole mesh with zero
    halo traffic — the deliberate v2 contrast with the rolling pipeline
    above, whose 31-byte window forces a ppermute ring. The only
    collective is the psum that aggregates global chunk-count telemetry.

    step(words_le [S, bps*16] u32 — strips sharded over ('dp','sp'),
         real_blocks [S] i32 — same sharding)
      -> (cutflag [bps, S] i32 (strips sharded on axis 1),
          states [bps*8, S] u32 (same),
          n_chunks [] i32 (global psum))
    """
    from dfs_tpu.ops.cdc_v2 import (gear_candidates_device,
                                    select_cuts_device)
    from dfs_tpu.ops.layout import bswap_transpose
    from dfs_tpu.ops.sha256_strip import strip_states, strip_states_xla

    on_tpu = all(d.platform == "tpu" for d in mesh.devices.flat)

    def local_step(words_le, real_blocks):
        words_t = bswap_transpose(words_le)           # local [bps*16, S/n]
        cand = gear_candidates_device(words_t, params)
        cutflag, _ = select_cuts_device(cand, real_blocks, params)
        cf32 = cutflag.astype(jnp.int32)
        # Pallas wants a 128-multiple lane dim; shapes are static at trace
        # time, so the local strip count decides per-compile.
        use_pallas = on_tpu and words_t.shape[1] % 128 == 0
        states = (strip_states if use_pallas else strip_states_xla)(
            words_t, cf32)
        n = jax.lax.psum(
            jax.lax.psum(jnp.sum(cf32), "sp"), "dp")
        return cf32, states, n

    shard_fn = _shard_map(
        local_step, mesh=mesh,
        in_specs=(P(("dp", "sp")), P(("dp", "sp"))),
        out_specs=(P(None, ("dp", "sp")), P(None, ("dp", "sp")), P()),
        check_vma=False,
    )
    return jax.jit(shard_fn)


def shard_aligned_inputs(mesh: Mesh, words_le: np.ndarray,
                         real_blocks: np.ndarray):
    """device_put aligned-step inputs with strip-axis sharding."""
    return (
        jax.device_put(words_le, NamedSharding(mesh, P(("dp", "sp")))),
        jax.device_put(real_blocks, NamedSharding(mesh, P(("dp", "sp")))),
    )


# ---------------------------------------------------------------------------
# anchored v3, sharded — the flagship's multi-device step
# ---------------------------------------------------------------------------

def make_anchored_anchor_step(mesh: Mesh, params, m_local: int):
    """Sharded **pass A** of the anchored pipeline (ops.cdc_anchored):
    the byte-granular anchor hash is elementwise, so the stream shards over
    the whole mesh as overlapping word spans with a 2-word (8-byte)
    lookback halo — prepared host-side by :func:`shard_anchor_inputs`, so
    no collective is needed at all (the halo is baked into each device's
    span, the anchored analogue of the rolling pipeline's ppermute ring).

    step(spans [n_dev, 2 + m_local] u32) -> tiles
    [2, n_dev * tiles_local] i32 (first-two-anchor byte positions per
    TILE_BYTES tile, region-local; row 0 < row 1 where present).
    """
    from dfs_tpu.ops.cdc_anchored import TILE_BYTES, make_anchor_fn

    local_fn = make_anchor_fn(params, m_local)
    tiles_local = m_local * 4 // TILE_BYTES

    def local_step(span):
        # span: [1, 2 + m_local] on this device; positions are local to
        # the span — rebase to region offsets with the device index.
        dev = jax.lax.axis_index("dp") * mesh.shape["sp"] \
            + jax.lax.axis_index("sp")
        tiles = local_fn(span[0])                   # [2, tiles_local]
        return (tiles + jnp.where(tiles < 2**30,
                                  dev * jnp.int32(m_local * 4),
                                  0))[None, :, :]

    shard_fn = _shard_map(
        local_step, mesh=mesh,
        in_specs=(P(("dp", "sp"), None),),
        out_specs=P(("dp", "sp"), None, None),
        check_vma=False,
    )
    return jax.jit(lambda spans: jnp.swapaxes(
        shard_fn(spans), 0, 1).reshape(
        2, mesh.devices.size * tiles_local))


def shard_anchor_inputs(mesh: Mesh, words: np.ndarray, m_local: int):
    """Build the overlapped per-device spans for pass A from a region
    buffer (ops.cdc_anchored.region_buffer layout: 2 lookback words then
    the region). Device d gets words [d*m_local, (d+1)*m_local] plus its
    2-word lookback — the overlap is 8 bytes per device boundary."""
    n_dev = mesh.devices.size
    spans = np.zeros((n_dev, 2 + m_local), dtype=np.uint32)
    for d in range(n_dev):
        lo = d * m_local
        spans[d] = words[lo:lo + 2 + m_local]
    return jax.device_put(
        spans, NamedSharding(mesh, P(("dp", "sp"), None)))


def make_anchored_step(mesh: Mesh, params):
    """Sharded **pass B** of the anchored pipeline: segments are fully
    independent lanes (the 64-byte chunk grid restarts at each segment
    start), so the segment axis shards over the whole mesh with zero halo
    traffic — same contrast with the rolling ppermute ring as the aligned
    step above. The region words stay replicated (every device repacks its
    own lanes by dynamic_slice; on a real pod the region would ride dp and
    only lane descriptors shard). The only collective is the chunk-count
    psum.

    step(words [W] u32 — replicated region buffer,
         w_off/sh8/real_blocks/tail_len [s_pad] — sharded over ('dp','sp'))
      -> (cutflag [bps, s_pad] i32 (lanes sharded on axis 1),
          since [bps, s_pad] i32 (same),
          n_chunks [] i32 (global psum))
    """
    from dfs_tpu.ops.cdc_v2 import (gear_candidates_device,
                                    select_cuts_device)
    from dfs_tpu.ops.layout import bswap_transpose
    from dfs_tpu.ops.repack import repack_lanes_xla
    from dfs_tpu.ops.sha256_strip import strip_states, strip_states_xla

    cp = params.chunk
    lane_words = cp.strip_blocks * 16
    on_tpu = all(d.platform == "tpu" for d in mesh.devices.flat)

    def local_step(words, w_off, sh8, real_blocks):
        # XLA repack form inside shard_map (per-shard Pallas dispatch is
        # not worth gating here); ops.repack owns the single definition
        packed = repack_lanes_xla(words, w_off, sh8, lane_words)
        words_t = bswap_transpose(packed)
        cand = gear_candidates_device(words_t, cp)
        cutflag, since = select_cuts_device(cand, real_blocks, cp)
        cf32 = cutflag.astype(jnp.int32)
        use_pallas = on_tpu and words_t.shape[1] % 128 == 0
        states = (strip_states if use_pallas else strip_states_xla)(
            words_t, cf32)
        n = jax.lax.psum(jax.lax.psum(jnp.sum(cf32), "sp"), "dp")
        return cf32, since, states, n

    shard_fn = _shard_map(
        local_step, mesh=mesh,
        in_specs=(P(), P(("dp", "sp")), P(("dp", "sp")), P(("dp", "sp")),),
        out_specs=(P(None, ("dp", "sp")), P(None, ("dp", "sp")),
                   P(None, ("dp", "sp")), P()),
        check_vma=False,
    )
    return jax.jit(shard_fn)


def shard_anchored_lane_inputs(mesh: Mesh, w_off: np.ndarray,
                               sh8: np.ndarray, real_blocks: np.ndarray):
    """device_put ONLY the pass-B lane descriptor arrays (sharded over
    the flattened mesh) — for callers whose region words are already
    device-resident (the sharded anchored streaming walk stages the
    region once per window and derives the lane tables after pass A)."""
    lane = NamedSharding(mesh, P(("dp", "sp")))
    return (
        jax.device_put(w_off, lane),
        jax.device_put(sh8, lane),
        jax.device_put(real_blocks, lane),
    )


def shard_anchored_inputs(mesh: Mesh, words: np.ndarray, w_off: np.ndarray,
                          sh8: np.ndarray, real_blocks: np.ndarray):
    """device_put anchored pass-B inputs: words replicated, lane
    descriptor arrays sharded over the flattened mesh."""
    return (
        jax.device_put(words, NamedSharding(mesh, P())),
        *shard_anchored_lane_inputs(mesh, w_off, sh8, real_blocks),
    )


def make_anchored_window_anchor_step(mesh: Mesh, params, m_words: int):
    """Window-BATCHED pass A of the anchored ingest walk (round 15):
    ``dp_size`` stream windows ride the mesh's dp axis, each device
    running the whole anchor pass (``ops.cdc_anchored.make_anchor_fn``
    — the single definition, same as the span-sharded
    :func:`make_anchored_anchor_step`) over its OWN window's region
    buffer. No halo, no collective: the 8-byte lookback is baked into
    each window's buffer host-side exactly as the single-device walk
    bakes it.

    Why windows-over-dp instead of spans-over-the-mesh: the ingest
    walk's scaling axis must match its pass-B step (below), and pass B
    is a SEQUENTIAL block scan whose wall-clock is chain-length-bound —
    sharding one window's lanes across devices thins the vectors
    without shortening the chain (measured near-FLAT, ~1.2x at 4
    virtual devices), while running whole windows per device scales
    throughput with the device count (3.85x resident at 4 — the
    CDC_SHARD_r15.json A/B).

    step(words [B, total_words] u32 — B == dp size, rows sharded over
    dp, replicated over sp) -> tiles [B, 2, m_tiles] i32 (per-window
    first-two-anchor tables, window-local positions)."""
    from dfs_tpu.ops.cdc_anchored import make_anchor_fn

    local_fn = make_anchor_fn(params, m_words)

    def local_step(words):
        return local_fn(words[0])[None]

    shard_fn = _shard_map(
        local_step, mesh=mesh,
        in_specs=(P("dp", None),),
        out_specs=P("dp", None, None),
        check_vma=False,
    )
    return jax.jit(shard_fn)


def make_anchored_window_step(mesh: Mesh, params, total_words: int,
                              s_pad: int):
    """Window-BATCHED pass B, INGEST edition (round 15): each device
    runs the whole single-device segment chain — Pallas/XLA repack,
    fused candidates/selection/SHA strip scan, cut compaction, on-device
    FIPS tail finalize (``ops.cdc_anchored.make_anchored_segment_fn`` —
    the ONE definition of that math) — on its OWN stream window,
    returning FINISHED (offset, length, digest) chunk tables. Pass A +
    host segment selection (the carry-threaded ``select_segments``)
    decide each window's lane tables; zero collectives on the data path.

    Two measured dead ends picked this shape (CDC_SHARD_r15.json A/Bs,
    96 MiB stream, 4 virtual devices):

    - pulling only cutflags (:func:`make_anchored_step` with the SHA
      outputs dropped) and hashing payloads on the host: 1.02x — the
      serial host SHA dominated;
    - sharding one window's segment LANES across the mesh with device
      SHA: 1.28x — the strip scan is SEQUENTIAL over blocks, so
      per-device wall time barely moves when only the lane axis thins
      (the resident step alone measured ~1.2x).

    Windows are independent given their carry, and the carry needs only
    pass A + host select — so windows ride dp, and throughput scales
    with devices (3.85x resident at 4) while each window's chain keeps
    its single-device latency.

    step(words [B, total_words] u32 — B == dp size, rows over dp,
         w_off/sh8/real_blocks/tail_len/starts/seg_lens [B, s_pad] i32/
         u32 — same row sharding)
      -> (count [B] i32, q [B, c_max] i32, offs [B, c_max] i32,
          lens [B, c_max] i32, digests [B, c_max, 8] u32)
    — row b is window b's chunk table in stream order.
    ``cap_mode='full'`` (capacities bound the worst case — a streaming
    walk must never need the synchronous overflow redo)."""
    from dfs_tpu.ops.cdc_anchored import make_anchored_segment_fn

    segfn = make_anchored_segment_fn(params, total_words, s_pad,
                                     cap_mode="full")

    def local_step(words, w_off, sh8, real_blocks, tail_len, starts,
                   seg_lens):
        count, q, offs, lens, dig = segfn(
            words[0], w_off[0], sh8[0], real_blocks[0], tail_len[0],
            starts[0], seg_lens[0])
        return (count[None], q[None], offs[None], lens[None], dig[None])

    row = P("dp", None)
    shard_fn = _shard_map(
        local_step, mesh=mesh,
        in_specs=(row, row, row, row, row, row, row),
        out_specs=(P("dp"), row, row, row, P("dp", None, None)),
        check_vma=False,
    )
    return jax.jit(shard_fn)


def host_lane_descriptors(data: np.ndarray, params, pad_multiple: int):
    """Host-side segment selection + pass-B lane descriptor encoding for
    a whole stream, shared by the dryrun parity check and the multihost
    test worker. The w_off/sh8/real_blocks layout itself comes from
    ``ops.cdc_anchored.lane_tables_np`` — the ONE host-side mirror of
    the device-side make_descriptor_fn encoding (the sharded ingest
    walk uses the same function per window). Returns (starts, bounds,
    seg_lens, w_off, sh8, real_blocks, s_real)."""
    from dfs_tpu.ops.cdc_anchored import (kept_anchors_np, lane_tables_np,
                                          select_segments)

    n = int(data.shape[0])
    bounds = select_segments(kept_anchors_np(data, params), n, params)
    starts = np.concatenate([[0], bounds[:-1]])
    seg_lens = bounds - starts
    s_real = starts.shape[0]
    s_pad = -(-s_real // pad_multiple) * pad_multiple
    _, _, w_off, sh8, real_blocks, _ = lane_tables_np(bounds, 0, s_pad)
    return starts, bounds, seg_lens, w_off, sh8, real_blocks, s_real


def expected_segment_cutflags(data: np.ndarray, starts, bounds,
                              params) -> np.ndarray:
    """Per-segment oracle cutflags [bps, s_real] for pass-B verification
    (NumPy candidates + greedy selection per segment)."""
    from dfs_tpu.ops.cdc_v2 import BLOCK, candidates_np, select_cuts_blocks

    bps = params.chunk.strip_blocks
    s_real = len(starts)
    out = np.zeros((bps, s_real), np.int32)
    for i in range(s_real):
        seg = data[int(starts[i]):int(bounds[i])]
        nb = -(-seg.shape[0] // BLOCK)
        pos = np.flatnonzero(candidates_np(seg, params.chunk))
        cuts = select_cuts_blocks(pos, nb, params.chunk)
        out[cuts - 1, i] = 1
    return out


def anchored_sharded_parity_check(mesh: Mesh, n_devices: int) -> None:
    """Run both sharded anchored passes on a tiny stream and assert parity
    with the NumPy oracles — shared by the driver's multichip dryrun
    (__graft_entry__) and the test suite so the two always validate the
    same contract (pass-A tiles == first-anchor-per-tile oracle, pass-B
    cutflags == per-segment selection, psum == population, reconstructed
    spans == whole-stream chunk_spans_anchored_np)."""
    from dfs_tpu.ops.cdc_anchored import (TILE_BYTES, AnchoredCdcParams,
                                          chunk_spans_anchored_np,
                                          kept_anchors_np, region_buffer)
    from dfs_tpu.ops.cdc_v2 import BLOCK, AlignedCdcParams

    params = AnchoredCdcParams(
        chunk=AlignedCdcParams(min_blocks=2, avg_blocks=4, max_blocks=16,
                               strip_blocks=64),        # 4 KiB lanes
        seg_min=2048, seg_max=4096, seg_mask=2047)

    m_local = 4 * TILE_BYTES // 4                       # 4 tiles per device
    m_words = m_local * n_devices
    n = m_words * 4
    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, size=n, dtype=np.uint8)
    words = np.asarray(region_buffer(data, np.zeros((8,), np.uint8), params,
                                     m_words=m_words))

    # ---- pass A sharded: tiles vs NumPy oracle ----
    astep = make_anchored_anchor_step(mesh, params, m_local)
    tiles = np.asarray(astep(shard_anchor_inputs(mesh, words, m_local)))
    kept = kept_anchors_np(data, params)
    expect_tiles = np.full((2, m_words * 4 // TILE_BYTES), 2**30, np.int32)
    for p in kept:                  # kept is first-two-per-tile, sorted
        t = int(p) // TILE_BYTES
        row = 0 if expect_tiles[0, t] == 2**30 else 1
        expect_tiles[row, t] = int(p)
    if not np.array_equal(tiles, expect_tiles):
        raise AssertionError("sharded anchored pass A tile mismatch")

    # ---- host segment selection (metadata-sized, shared with oracle) ----
    (starts, bounds, seg_lens, w_off, sh8, real_blocks,
     s_real) = host_lane_descriptors(data, params, n_devices)

    # ---- pass B sharded: per-segment cutflags vs oracle ----
    bstep = make_anchored_step(mesh, params)
    cf, since, _states, n_chunks = bstep(*shard_anchored_inputs(
        mesh, words, w_off, sh8, real_blocks))
    cf = np.asarray(cf)
    expect = expected_segment_cutflags(data, starts, bounds, params)
    if not np.array_equal(cf[:, :s_real], expect):
        raise AssertionError("anchored sharded cutflag mismatch")
    if int(n_chunks) != int(cf.sum()):
        raise AssertionError("anchored psum chunk count mismatch")

    # ---- end-to-end span parity vs the whole-stream oracle ----
    spans = []
    for i in range(s_real):
        ln = int(seg_lens[i])
        cuts = np.flatnonzero(cf[:, i]) + 1
        prev = 0
        for c in cuts.tolist():
            end = min(c * BLOCK, ln)
            spans.append((int(starts[i]) + prev * BLOCK,
                          end - prev * BLOCK))
            prev = c
    if spans != chunk_spans_anchored_np(data, params):
        raise AssertionError("anchored sharded spans != oracle spans")


def anchored_sharded_production_check(mesh: Mesh, n_devices: int,
                                      region_bytes: int = 64 * 2**20,
                                      ) -> dict:
    """The parity check above at PRODUCTION geometry: a full 64 MiB
    region, default AnchoredCdcParams (96-128 KiB segments, 128 KiB
    lanes), lane tables padded to lane_multiple=128 — the exact shapes
    the single-chip chain ships with (`__graft_entry__.entry` uses
    production lane_multiple but toy segments; the toy-mesh check uses
    4-tile devices). This exercises what those cannot: lane-table
    provisioning at ~640 real lanes, halo/rebase correctness at 16K
    tiles per device, and the [2, n_tiles] two-anchor planes across
    device boundaries. Oracle-checked end to end (pass-A tiles, pass-B
    cutflags per segment, psum, reconstructed spans == whole-stream
    oracle). Returns a timing/shape record for the committed artifact
    (wall times; on a virtual CPU mesh all devices share the host, so
    per-step wall time is the honest number — per-device counters would
    fabricate parallelism the harness does not have)."""
    import time

    from dfs_tpu.ops.cdc_anchored import (TILE_BYTES, AnchoredCdcParams,
                                          chunk_spans_anchored_np,
                                          kept_anchors_np, region_buffer)
    from dfs_tpu.ops.cdc_v2 import BLOCK

    params = AnchoredCdcParams()               # production geometry
    lane_multiple = 128
    n = (region_bytes // TILE_BYTES) * TILE_BYTES
    m_words = n // 4
    if m_words % n_devices:
        raise ValueError("region words must split evenly over devices")
    m_local = m_words // n_devices
    if (m_local * 4) % TILE_BYTES:
        raise ValueError("per-device span must be tile-aligned")

    rng = np.random.default_rng(23)
    data = rng.integers(0, 256, size=n, dtype=np.uint8)
    words = np.asarray(region_buffer(data, np.zeros((8,), np.uint8),
                                     params, m_words=m_words))
    rec: dict = {"region_bytes": n, "n_devices": n_devices,
                 "m_local_words": m_local,
                 "tiles_per_device": m_local * 4 // TILE_BYTES,
                 "params": {"seg_min": params.seg_min,
                            "seg_max": params.seg_max,
                            "strip_blocks": params.chunk.strip_blocks,
                            "lane_multiple": lane_multiple}}

    # ---- pass A sharded at production scale ----
    astep = make_anchored_anchor_step(mesh, params, m_local)
    inp = shard_anchor_inputs(mesh, words, m_local)
    t0 = time.perf_counter()
    tiles = np.asarray(jax.block_until_ready(astep(inp)))
    rec["pass_a_s"] = round(time.perf_counter() - t0, 3)
    kept = kept_anchors_np(data, params)
    expect_tiles = np.full((2, m_words * 4 // TILE_BYTES), 2**30, np.int32)
    for p in kept:
        t = int(p) // TILE_BYTES
        row = 0 if expect_tiles[0, t] == 2**30 else 1
        expect_tiles[row, t] = int(p)
    if not np.array_equal(tiles, expect_tiles):
        raise AssertionError("production sharded pass A tile mismatch")
    rec["kept_anchors"] = int(kept.shape[0])

    # ---- host selection + production lane tables ----
    (starts, bounds, seg_lens, w_off, sh8, real_blocks,
     s_real) = host_lane_descriptors(data, params, lane_multiple)
    if w_off.shape[0] % n_devices:
        raise AssertionError(
            f"lane table {w_off.shape[0]} not divisible by {n_devices}")
    rec["segments"] = int(s_real)
    rec["lane_table"] = int(w_off.shape[0])

    # ---- pass B sharded at production scale ----
    bstep = make_anchored_step(mesh, params)
    binp = shard_anchored_inputs(mesh, words, w_off, sh8, real_blocks)
    t0 = time.perf_counter()
    cf, since, _states, n_chunks = jax.block_until_ready(bstep(*binp))
    rec["pass_b_s"] = round(time.perf_counter() - t0, 3)
    cf = np.asarray(cf)
    expect = expected_segment_cutflags(data, starts, bounds, params)
    if not np.array_equal(cf[:, :s_real], expect):
        raise AssertionError("production sharded cutflag mismatch")
    if int(n_chunks) != int(cf.sum()):
        raise AssertionError("production sharded psum mismatch")
    rec["chunks"] = int(n_chunks)

    # ---- end-to-end span parity vs the whole-stream oracle ----
    spans = []
    for i in range(s_real):
        ln = int(seg_lens[i])
        cuts = np.flatnonzero(cf[:, i]) + 1
        prev = 0
        for c in cuts.tolist():
            end = min(c * BLOCK, ln)
            spans.append((int(starts[i]) + prev * BLOCK, end - prev * BLOCK))
            prev = c
    if spans != chunk_spans_anchored_np(data, params):
        raise AssertionError("production sharded spans != oracle spans")
    return rec


# ---------------------------------------------------------------------------
# min-hash sketches, sharded — chunks ride dp, one batch row per device
# ---------------------------------------------------------------------------

def make_sketch_step(mesh: Mesh, lanes_a: np.ndarray, lanes_b: np.ndarray,
                     shingle_bytes: int, window_bytes: int,
                     mult: int):
    """Batched **min-hash sketch** step of the similarity plane (round
    21, dfs_tpu.sim): ``dp_size`` chunks ride the mesh's dp axis — the
    same windows-over-dp shape the anchored ingest walk settled on
    (each lane's min is a full reduction over the chunk's shingles, so
    thinning the shingle axis would not shorten any chain; whole chunks
    per device scale throughput with the device count). No halo, no
    collective: a chunk's shingles never cross its row.

    All arithmetic is uint32 with wraparound, matching
    ``dfs_tpu.sim.sketch.sketch_np`` EXACTLY (JAX's 32-bit default is
    the oracle's dtype): rolling polynomial shingle hash over
    ``shingle_bytes`` (static unrolled loop), then per-lane
    ``min(h * a + b)`` with positions past the chunk's real length
    masked to the empty-lane sentinel. The lane permute + mask + min
    runs TILED (``fori_loop`` over position tiles with a running
    ``[n_lanes]`` minimum): the whole ``[n_lanes, n_pos]`` value matrix
    never materializes, each tile's values stay cache-resident through
    their reduce, and the mask folds in as a bitwise OR of a
    per-position penalty (valid -> ``|0``, invalid -> ``|0xFFFFFFFF``
    == the empty sentinel) — ~5x over the naive broadcast-then-reduce
    on the CPU backend, bit-for-bit the same minima.

    step(blocks [G, W] u8 — G a multiple of dp, rows sharded over dp
         (each device sketches G/dp whole chunks per dispatch, vmapped),
         lens [G] i32 — same row sharding)
      -> sketches [G, n_lanes] u32 (row sharding)."""
    a_j = jnp.asarray(lanes_a, dtype=jnp.uint32)
    b_j = jnp.asarray(lanes_b, dtype=jnp.uint32)
    mult_j = jnp.uint32(mult)
    n_lanes = int(a_j.shape[0])
    n_pos = window_bytes - shingle_bytes + 1
    empty = jnp.uint32(0xFFFFFFFF)
    tile = min(512, window_bytes)    # [n_lanes, tile] u32 stays L1-ish
    n_tiles = -(-n_pos // tile)
    pad = n_tiles * tile

    def one(block, ln):
        bb = block.astype(jnp.uint32)
        h = jnp.zeros((n_pos,), jnp.uint32)
        for j in range(shingle_bytes):
            h = h * mult_j + jax.lax.slice_in_dim(bb, j, j + n_pos)
        pen = jnp.where(jnp.arange(n_pos, dtype=jnp.int32)
                        < jnp.maximum(ln - shingle_bytes + 1, 0),
                        jnp.uint32(0), empty)
        hp = jnp.zeros((pad,), jnp.uint32).at[:n_pos].set(h)
        penp = jnp.full((pad,), empty, jnp.uint32).at[:n_pos].set(pen)

        def body(t, acc):
            hs = jax.lax.dynamic_slice(hp, (t * tile,), (tile,))
            ps = jax.lax.dynamic_slice(penp, (t * tile,), (tile,))
            vals = (hs[None, :] * a_j[:, None] + b_j[:, None]) \
                | ps[None, :]
            return jnp.minimum(acc, vals.min(axis=1))

        return jax.lax.fori_loop(
            0, n_tiles, body, jnp.full((n_lanes,), empty, jnp.uint32))

    def local_step(blocks, lns):
        return jax.vmap(one)(blocks, lns)

    shard_fn = _shard_map(
        local_step, mesh=mesh,
        in_specs=(P("dp", None), P("dp")),
        out_specs=P("dp", None),
        check_vma=False,
    )
    return jax.jit(shard_fn)


# ---------------------------------------------------------------------------
# erasure parity, sharded — stripes are independent; pure data parallelism
# ---------------------------------------------------------------------------

def make_ec_step(mesh: Mesh, k: int):
    """Multi-device erasure-parity encode (ops.ec P+Q over GF(256)).

    Stripes encode independently, so the stripe axis shards over the
    whole flattened ('dp','sp') mesh with ZERO collectives on the data
    path — parity is xor + the xtime funnel per stripe, memory-bound
    VPU work on every device at once. The only collective is the psum'd
    parity-byte telemetry (what the node runtime reports as
    ecParityBytes).

    step(stripes [NS, k, n] u32 — stripe axis sharded)
      -> (p [NS, n] u32, q [NS, n] u32 (same sharding),
          parity_bytes [] i64-ish i32 (global psum))
    """
    from dfs_tpu.ops.ec import pq_horner

    def local_step(stripes):
        p, q = pq_horner(stripes, k, axis=1)
        nbytes = jax.lax.psum(jax.lax.psum(
            jnp.int32(2 * 4) * stripes.shape[0] * stripes.shape[2],
            "sp"), "dp")
        return p, q, nbytes

    shard_fn = _shard_map(
        local_step, mesh=mesh,
        in_specs=(P(("dp", "sp")),),
        out_specs=(P(("dp", "sp")), P(("dp", "sp")), P()),
        check_vma=False,
    )
    return jax.jit(shard_fn)


def shard_ec_inputs(mesh: Mesh, stripes: np.ndarray):
    """device_put EC-step input with stripe-axis sharding."""
    return jax.device_put(
        stripes, NamedSharding(mesh, P(("dp", "sp"))))
