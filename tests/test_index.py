"""Dedup/index plane (dfs_tpu/index, docs/index.md).

Layers of coverage:

- UNIT: LSI round-trip through flush + compaction, torn-WAL-tail
  truncation, corrupt-run → rebuild-from-CAS, the blocked bloom's
  no-false-negative contract, and the filter delta/resync protocol
  including the corrupted-delta → full-resync path.
- DEFAULT-OFF IDENTITY: ``IndexConfig()`` builds no plane, no store
  seam, no sync loop — the zero-knob node runs the historical
  stat-per-digest paths (the chaos/serve discipline).
- CRASH SAFETY (real ``kill -9``): a child process feeds a real
  ChunkStore+DigestIndex and SIGKILLs itself mid-compaction (the
  DigestIndex hook seam — deterministic, before the CURRENT commit)
  and mid-append; the parent reopens and asserts the index's answers
  match a fresh CAS walk, with zero false positives (the one
  divergence direction the design forbids). Same discipline as the
  r11 journal torn-tail test.
- CLUSTER: filter gossip replicates, re-upload placement skips probe
  RPCs with copies verified pre-ack, a POISONED filter (forced false
  positive) is detected at verification and healed by a real transfer
  before the ack, and repair's probe trim never deletes strays on a
  bloom maybe.
- BENCH: ``bench_dedup_index.py --tiny`` subprocess smoke + schema
  lock for the committed DEDUP_INDEX_r16.json.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import textwrap
import time
from pathlib import Path

import pytest

from dfs_tpu.config import (CDCParams, CensusConfig, ClusterConfig,
                            IndexConfig, NodeConfig, PeerAddr)
from dfs_tpu.index import DELTA_CAP, IndexPlane
from dfs_tpu.index.filter import (BlockedBloomFilter, LocalFilter,
                                  PeerFilterSet)
from dfs_tpu.index.lsi import DigestIndex
from dfs_tpu.node.runtime import StorageNodeServer
from dfs_tpu.store.cas import ChunkStore
from dfs_tpu.utils.hashing import sha256_hex

REPO = Path(__file__).resolve().parent.parent
CDC = CDCParams(min_size=2048, avg_size=8192, max_size=65536)
CENSUS_OFF = CensusConfig(history_interval_s=0)


def _digests(n: int, tag: str = "") -> list[str]:
    return [sha256_hex(f"{tag}{i}".encode()) for i in range(n)]


# ------------------------------------------------------------------ #
# unit: log-structured index
# ------------------------------------------------------------------ #

def test_lsi_roundtrip_through_flush_and_compaction(tmp_path):
    """Puts and deletes survive memtable flushes and full compactions;
    lookups answer identically before and after reopen."""
    idx = DigestIndex(tmp_path / "ix", memtable_entries=256,
                      compact_runs=2)
    assert idx.open_or_rebuild(lambda: [])["rebuilt"] is False
    present = _digests(3000, "p")
    gone = _digests(300, "g")
    for d in present + gone:
        idx.note_put(d)
    for d in gone:
        idx.note_delete(d)
    assert idx.stats()["compactions"] > 0   # tiny memtable forced them
    assert all(idx.lookup(d) for d in present)
    assert not any(idx.lookup(d) for d in gone)
    assert not idx.lookup(sha256_hex(b"never-stored"))
    idx.close()

    idx2 = DigestIndex(tmp_path / "ix", memtable_entries=256,
                       compact_runs=2)
    info = idx2.open_or_rebuild(lambda: pytest.fail("no rebuild"))
    assert info["rebuilt"] is False
    assert all(idx2.lookup(d) for d in present)
    assert not any(idx2.lookup(d) for d in gone)
    idx2.close()


def test_lsi_torn_wal_tail_truncated_not_fatal(tmp_path):
    """A torn trailing WAL record (kill -9 mid-append) is discarded on
    replay; every record before it survives."""
    idx = DigestIndex(tmp_path / "ix", memtable_entries=4096)
    idx.open_or_rebuild(lambda: [])
    ds = _digests(10)
    for d in ds:
        idx.note_put(d)
    idx.close()
    cur = json.loads((tmp_path / "ix" / "CURRENT").read_bytes())
    with open(tmp_path / "ix" / cur["wal"], "ab") as f:
        f.write(b"\x01torn-mid-record")
    idx2 = DigestIndex(tmp_path / "ix", memtable_entries=4096)
    info = idx2.open_or_rebuild(lambda: [])
    assert info["rebuilt"] is False   # a torn tail is NOT corruption
    assert all(idx2.lookup(d) for d in ds)
    idx2.close()


def test_lsi_corrupt_run_rebuilds_from_cas_walk(tmp_path):
    """Structural damage (a flipped run byte breaks the footer crc)
    degrades to a rebuild from the CAS walk — ground truth wins."""
    idx = DigestIndex(tmp_path / "ix", memtable_entries=256)
    idx.open_or_rebuild(lambda: [])
    for d in _digests(600, "x"):
        idx.note_put(d)
    idx.close()
    run = next(p for p in (tmp_path / "ix").iterdir()
               if p.suffix == ".idx")
    data = bytearray(run.read_bytes())
    data[40] ^= 0xFF
    run.write_bytes(data)
    truth = _digests(50, "truth")
    events = []
    idx2 = DigestIndex(tmp_path / "ix", memtable_entries=256)
    idx2.on_event = lambda etype, **kw: events.append((etype, kw))
    info = idx2.open_or_rebuild(lambda: truth)
    assert info["rebuilt"] is True and info["entries"] == 50
    assert all(idx2.lookup(d) for d in truth)
    assert not idx2.lookup(_digests(1, "x")[0])
    assert [e for e, _ in events] == ["index_rebuild"]  # journaled
    idx2.close()


def test_lsi_fence_prefix_collision_across_blocks(tmp_path):
    """Fences hold 8-byte prefixes, which are ambiguous at block
    boundaries: thousands of digests sharing one prefix must all be
    found (the back-walk), and a tombstone in a newer run must never
    be missed in favor of an older run's stale 'present' (the
    resurrection the code-review fence finding described)."""
    idx = DigestIndex(tmp_path / "ix", memtable_entries=256,
                      compact_runs=2)
    idx.open_or_rebuild(lambda: [])
    prefix = "ab" * 8                       # one shared 8-byte prefix
    same = sorted(prefix + sha256_hex(str(i).encode())[16:]
                  for i in range(3000))     # ~3 fence blocks of one
    for d in same:                          # prefix after compaction
        idx.note_put(d)
    assert all(idx.lookup(d) for d in same)
    # tombstone digests across the span (first/boundary/last), then
    # force them into a NEWER run than the base holding the puts
    victims = [same[0], same[1023], same[1024], same[-1]]
    for d in victims:
        idx.note_delete(d)
    for d in _digests(600, "churn"):        # flush + fold the deletes
        idx.note_put(d)
    assert not any(idx.lookup(d) for d in victims)
    assert all(idx.lookup(d) for d in same if d not in victims)
    idx.close()


def test_lsi_wal_bounded_under_same_key_churn(tmp_path):
    """Repeated store/delete of ONE working set must not grow the WAL
    without bound: the record-count trigger flushes even though the
    memtable's distinct-key count never reaches its cap."""
    idx = DigestIndex(tmp_path / "ix", memtable_entries=256,
                      compact_runs=2)
    idx.open_or_rebuild(lambda: [])
    ds = _digests(16, "churn")
    for _ in range(400):                    # 6400 records, 16 keys
        for d in ds:
            idx.note_put(d)
    idx.flush()
    assert idx.stats()["walRecords"] <= 8 * 256
    wal = [p for p in (tmp_path / "ix").iterdir()
           if p.name.startswith("wal-")]
    assert all(p.stat().st_size <= 8 * 256 * 37 for p in wal)
    assert all(idx.lookup(d) for d in ds)
    idx.close()


def test_lsi_lookups_race_compactions_without_errors(tmp_path):
    """Unlocked run preads vs concurrent compactions (the retired-fd
    race): reader threads hammer lookups while the writer forces
    continual flush+compaction cycles — every answer must be correct
    and no reader may ever see an EBADF/garbage read."""
    import threading

    idx = DigestIndex(tmp_path / "ix", memtable_entries=256,
                      compact_runs=1)       # compact on every flush
    idx.open_or_rebuild(lambda: [])
    stable = _digests(1200, "stable")
    for d in stable:
        idx.note_put(d)
    absent = _digests(400, "absent")
    errors: list[BaseException] = []
    stop = threading.Event()

    def reader() -> None:
        try:
            while not stop.is_set():
                for d in stable[::97]:
                    assert idx.lookup(d)
                for d in absent[::37]:
                    assert not idx.lookup(d)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=reader) for _ in range(4)]
    for t in threads:
        t.start()
    for d in _digests(4000, "writer"):      # ~15 flush+compact cycles
        idx.note_put(d)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    assert errors == []
    assert idx.stats()["compactions"] >= 5
    assert all(idx.lookup(d) for d in stable)
    idx.close()


def test_chunkstore_feed_and_has_fast_path(tmp_path):
    """The ChunkStore seam: put/delete feed the plane, has() trusts
    index positives (no stat) and stat-backstops negatives."""
    store = ChunkStore(tmp_path / "chunks")
    plane = IndexPlane(IndexConfig(enabled=True), tmp_path)
    plane.open_or_rebuild(store.digests)
    store.index = plane
    payload = b"chunk-payload" * 100
    d = sha256_hex(payload)
    assert store.put(d, payload)
    assert plane.lookup(d)                  # fed by the put
    assert store.has(d)
    # negative backstop: a chunk written BEHIND the index (external
    # writer / pre-index store / crash-lost WAL buffer) is still found
    # by the stat — and the backstop SELF-HEALS the index, so the miss
    # is paid once, not on every future probe
    sneak = b"sneaky" * 50
    ds = sha256_hex(sneak)
    store.index = None
    assert store.put(ds, sneak)
    store.index = plane
    assert not plane.lookup(ds)
    assert store.has(ds)                    # stat backstop
    assert plane.lookup(ds)                 # ...which healed the index
    # delete is recorded: index answers absent afterwards
    assert store.delete(d)
    assert not plane.lookup(d)
    assert not store.has(d)
    plane.close()


# ------------------------------------------------------------------ #
# unit: filters + delta protocol
# ------------------------------------------------------------------ #

def test_bloom_no_false_negatives_and_bounded_fp():
    bloom = BlockedBloomFilter(4096, bits_per_key=10)
    members = _digests(4096, "m")
    for d in members:
        bloom.add(d)
    assert all(bloom.contains(d) for d in members)   # never a false no
    others = _digests(4096, "o")
    fp = sum(1 for d in others if bloom.contains(d))
    assert fp / len(others) < 0.05   # ~2% expected at this density


def test_filter_delta_then_generation_bump_forces_resync():
    f = LocalFilter(bits_per_key=10)
    first = _digests(100, "a")
    for d in first:
        f.add(d)
    meta, body = f.snapshot()
    ps = PeerFilterSet()
    ps.apply_full(7, meta, body)
    assert all(ps.contains(7, d) for d in first)
    more = _digests(40, "b")
    for d in more:
        f.add(d)
    delta = f.delta(meta["gen"], meta["version"])
    assert delta["resync"] is False and len(delta["adds"]) == 40
    assert ps.apply_delta(7, delta["gen"], delta["version"],
                          delta["adds"])
    assert all(ps.contains(7, d) for d in more)
    # rebuild (compaction) changes the generation: the old cursor must
    # be told to resync — deltas cannot unlearn deletes
    f.rebuild([bytes.fromhex(d) for d in first])
    assert f.generation != meta["gen"]
    assert f.delta(meta["gen"], meta["version"])["resync"] is True
    # generations are RANDOM per life/rebuild: a restarted node's
    # fresh filter must never collide with its crashed life's cursor
    assert LocalFilter().generation != LocalFilter().generation
    # far-behind cursor (add log exhausted) also resyncs
    for d in _digests(DELTA_CAP + 100, "flood"):
        f.add(d)
    assert f.delta(f.generation, 0)["resync"] is True


def test_corrupted_delta_rejected_then_full_resync_recovers():
    """A malformed delta must not poison the replica — apply_delta
    refuses it, and the caller's full-resync path converges (the
    at-least-once discipline the runtime sync loop implements)."""
    f = LocalFilter(bits_per_key=10)
    for d in _digests(50, "a"):
        f.add(d)
    meta, body = f.snapshot()
    ps = PeerFilterSet()
    ps.apply_full(3, meta, body)
    # corrupt shapes: non-list adds, non-hex digest, version regress
    assert not ps.apply_delta(3, meta["gen"], meta["version"] + 1,
                              "not-a-list")
    assert not ps.apply_delta(3, meta["gen"], meta["version"] + 1,
                              ["zz-not-hex"])
    assert not ps.apply_delta(3, meta["gen"], meta["version"] - 10, [])
    assert not ps.apply_delta(3, meta["gen"] + 5, meta["version"], [])
    # the replica survived untouched and a full resync still lands
    for d in _digests(20, "late"):
        f.add(d)
    meta2, body2 = f.snapshot()
    ps.apply_full(3, meta2, body2)
    st = ps.state(3)
    assert st["version"] == meta2["version"]
    assert all(ps.contains(3, d) for d in _digests(20, "late"))


def test_fp_override_breaks_retrust():
    f = LocalFilter(bits_per_key=10)
    d = _digests(1, "fp")[0]
    f.add(d)
    meta, body = f.snapshot()
    ps = PeerFilterSet()
    ps.apply_full(2, meta, body)
    assert ps.contains(2, d) is True
    ps.note_fp(2, d)
    assert ps.contains(2, d) is False      # override beats the bloom
    assert ps.fp_observed == 1
    ps.apply_full(2, meta, body)           # resync re-judges
    assert ps.contains(2, d) is True


# ------------------------------------------------------------------ #
# default-off identity
# ------------------------------------------------------------------ #

def _mk_cluster(n: int, rf: int) -> ClusterConfig:
    socks, ports = [], []
    for _ in range(2 * n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    peers = tuple(PeerAddr(node_id=i + 1, host="127.0.0.1",
                           port=ports[2 * i],
                           internal_port=ports[2 * i + 1])
                  for i in range(n))
    return ClusterConfig(peers=peers, replication_factor=rf)


async def _start_nodes(cluster, root, index=None, **kw):
    nodes = {}
    for p in cluster.peers:
        cfg = NodeConfig(node_id=p.node_id, cluster=cluster,
                         data_root=root, fragmenter="cdc", cdc=CDC,
                         health_probe_s=0, census=CENSUS_OFF,
                         index=index or IndexConfig(), **kw)
        n = StorageNodeServer(cfg)
        await n.start()
        nodes[p.node_id] = n
    return nodes


async def _stop_all(nodes) -> None:
    for n in nodes.values():
        await n.stop()


def test_default_config_builds_no_plane(tmp_path):
    """IndexConfig() means NO plane: no store seam, no filter task, and
    /metrics reports the plane disabled — the zero-knob node runs the
    historical stat-per-digest code paths exactly."""
    assert IndexConfig() == IndexConfig(enabled=False)

    async def run() -> None:
        cluster = _mk_cluster(1, rf=1)
        nodes = await _start_nodes(cluster, tmp_path)
        node = nodes[1]
        try:
            assert node.index is None
            assert node.store.chunks.index is None
            assert node._filter_sync_task is None
            st = node.index_stats()
            assert st["enabled"] is False and "lsi" not in st
            # the data path still works (and no index dir appears)
            m, _ = await node.upload(b"identity" * 4000, "f.bin")
            _, body = await node.download(m.file_id)
            assert bytes(body) == b"identity" * 4000
            assert not (node.store.root / "index").exists()
        finally:
            await _stop_all(nodes)

    asyncio.run(run())


# ------------------------------------------------------------------ #
# crash safety: real kill -9, mid-compaction and mid-append
# ------------------------------------------------------------------ #

_CRASH_CHILD = textwrap.dedent("""
    import os, signal, sys
    sys.path.insert(0, {repo!r})
    from dfs_tpu.config import IndexConfig
    from dfs_tpu.index import IndexPlane
    from dfs_tpu.store.cas import ChunkStore
    from dfs_tpu.utils.hashing import sha256_hex

    root = {root!r}
    mode = {mode!r}
    store = ChunkStore(os.path.join(root, "chunks"))
    plane = IndexPlane(IndexConfig(enabled=True, memtable_entries=256,
                                   compact_runs=2), root)
    plane.open_or_rebuild(store.digests)
    store.index = plane
    compactions = 0
    def hook(point):
        global compactions
        compactions += 1
        if mode == "compact" and compactions >= 3:
            print("KILLING-MID-COMPACTION", flush=True)
            os.kill(os.getpid(), signal.SIGKILL)
    plane.lsi.hook = hook
    i = 0
    while True:
        payload = (b"crash-corpus-%d" % i) * 40
        d = sha256_hex(payload)
        store.put(d, payload)
        if i % 7 == 3 and i > 100:
            # interleave deletes: the written-through delete record is
            # the crash-ordering half the parent asserts on
            gone = (b"crash-corpus-%d" % (i - 100)) * 40
            store.delete(sha256_hex(gone))
        i += 1
        if i % 500 == 0:
            print("PROGRESS", i, flush=True)
""")


def _run_crash_child(tmp_path: Path, mode: str) -> None:
    child = tmp_path / "child.py"
    child.write_text(_CRASH_CHILD.format(repo=str(REPO),
                                         root=str(tmp_path / "store"),
                                         mode=mode))
    proc = subprocess.Popen(
        [sys.executable, str(child)], cwd=tmp_path,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    if mode == "compact":
        # the child SIGKILLs ITSELF inside the 3rd compaction — before
        # the CURRENT commit, deterministically mid-compaction
        rc = proc.wait(timeout=120)
        assert rc == -signal.SIGKILL
        assert "KILLING-MID-COMPACTION" in (proc.stdout.read() or "")
    else:
        # mid-append: let it write for a moment, then kill -9 from
        # outside at an arbitrary instant (high probability of landing
        # inside a WAL append / flush — the journal-test discipline)
        deadline = time.time() + 60
        while time.time() < deadline:
            line = proc.stdout.readline()
            if line.startswith("PROGRESS"):
                break
        else:
            pytest.fail("crash child made no progress")
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)


@pytest.mark.parametrize("mode", ["compact", "append"])
def test_kill9_index_reopens_consistent_with_cas_walk(tmp_path, mode):
    """After a real SIGKILL mid-compaction (deterministic, via the
    DigestIndex hook seam) or mid-append, the reopened index must
    answer consistently with a fresh CAS walk: ZERO false positives
    (every index-present digest exists on disk) and has() — index fast
    path plus stat backstop — exactly equal to the walk for both
    present and absent digests."""
    _run_crash_child(tmp_path, mode)
    root = tmp_path / "store"
    store = ChunkStore(root / "chunks")
    walk = set(store.digests())
    assert walk, "child stored nothing before dying"
    plane = IndexPlane(IndexConfig(enabled=True, memtable_entries=256,
                                   compact_runs=2), root)
    info = plane.open_or_rebuild(store.digests)
    store.index = plane
    # candidate universe: everything the child could have written or
    # deleted, present or not
    universe = [sha256_hex((b"crash-corpus-%d" % i) * 40)
                for i in range(20000)]
    false_pos = [d for d in universe
                 if plane.lookup(d) and d not in walk]
    assert false_pos == [], (
        f"{len(false_pos)} stale-present digests after {mode} crash "
        f"(rebuilt={info['rebuilt']})")
    for d in universe[:4000]:
        assert store.has(d) == (d in walk)
    plane.close()


# ------------------------------------------------------------------ #
# cluster: gossip + probe skipping + FP healing
# ------------------------------------------------------------------ #

def test_cluster_filter_gossip_and_reupload_probe_skip(tmp_path):
    """Filters replicate via the sync round; a re-upload then credits
    every remote copy from the filters (zero transfer), issues only
    the pre-ack verification probes, and a fresh upload after that
    skips probe RPCs entirely (all digests ruled out)."""
    ix = IndexConfig(enabled=True, memtable_entries=1024,
                     filter_sync_s=0)   # synced explicitly below

    async def run() -> None:
        cluster = _mk_cluster(3, rf=2)
        nodes = await _start_nodes(cluster, tmp_path, index=ix)
        try:
            data = os.urandom(400_000)
            m, s1 = await nodes[1].upload(data, "a.bin")
            assert s1["transferredBytes"] > 0
            for n in nodes.values():
                assert await n._filter_sync_once() == 2
            probes_before = _client_probe_rpcs(nodes[1])
            m2, s2 = await nodes[1].upload(data, "again.bin")
            probes_during = _client_probe_rpcs(nodes[1]) - probes_before
            assert s2["transferredBytes"] == 0
            assert s2["dedupSkippedBytes"] == s1["transferredBytes"]
            assert s2["minCopies"] >= 2          # verified, not hoped
            st = nodes[1].index_stats()
            assert st["filterTrusted"] > 0
            assert st["probesSkipped"] >= st["filterTrusted"]
            assert st["filterFp"] == 0
            # only the verification round probed: one RPC per peer
            assert probes_during <= 2
            # fresh data: every digest ruled out -> zero probe RPCs
            rpcs_before = _client_probe_rpcs(nodes[1])
            skipped_before = st["probeRpcsSkipped"]
            m3, s3 = await nodes[1].upload(os.urandom(200_000), "b.bin")
            assert _client_probe_rpcs(nodes[1]) == rpcs_before
            assert nodes[1].index_stats()["probeRpcsSkipped"] \
                > skipped_before
            # everything still reads back from every node
            for fid, want in ((m.file_id, data),):
                for n in nodes.values():
                    _, body = await n.download(fid)
                    assert bytes(body) == want
        finally:
            await _stop_all(nodes)

    asyncio.run(run())


def _client_probe_rpcs(node) -> int:
    return sum(row[0] for peer, op, row in node.obs.rpc_client.rows()
               if op == "has_chunks")


def test_poisoned_filter_fp_detected_and_healed_before_ack(tmp_path):
    """Force a false positive: poison node 1's replica of node 2's
    filter with the digests of an upload node 2 does NOT hold. The
    trusted credits must fail pre-ack verification, be counted as
    observed FPs, and be healed by a REAL transfer — after the ack the
    bytes exist on the peer (no phantom copies) and the file reads
    back from it."""
    ix = IndexConfig(enabled=True, filter_sync_s=0)

    async def run() -> None:
        cluster = _mk_cluster(2, rf=2)
        nodes = await _start_nodes(cluster, tmp_path, index=ix)
        try:
            seed = await nodes[1].upload(b"seed" * 3000, "seed.bin")
            for n in nodes.values():
                await n._filter_sync_once()
            data = os.urandom(120_000)
            manifest = nodes[1].fragmenter.manifest(
                data, name="x", file_id=sha256_hex(data))
            st2 = nodes[1].index.peer_filters.state(2)
            for c in manifest.chunks:
                st2["bloom"].add(c.digest)     # the lie
            m, stats = await nodes[1].upload(data, "x.bin")
            ixs = nodes[1].index_stats()
            assert ixs["filterFp"] > 0
            # healed: node 2 genuinely holds every chunk
            for c in m.chunks:
                assert nodes[2].store.chunks.has(c.digest)
            _, body = await nodes[2].download(m.file_id)
            assert bytes(body) == data
            # the heal transferred real bytes and un-counted the
            # phantom dedup credit
            assert stats["transferredBytes"] > 0
            assert seed is not None
        finally:
            await _stop_all(nodes)

    asyncio.run(run())


def test_repair_probe_trim_never_trusts_positives(tmp_path):
    """Repair consults filters only for the NEGATIVE side (skip probe
    payload for ruled-out digests); confirmations that gate stray
    deletion stay real has_chunks answers. A cycle after a heal still
    converges — and a poisoned positive cannot make repair skip a
    push it owes."""
    ix = IndexConfig(enabled=True, filter_sync_s=0)

    async def run() -> None:
        cluster = _mk_cluster(2, rf=2)
        nodes = await _start_nodes(cluster, tmp_path, index=ix)
        try:
            data = os.urandom(150_000)
            m, _ = await nodes[1].upload(data, "r.bin")
            for n in nodes.values():
                await n._filter_sync_once()
            # node 2 loses a chunk; node 1's replica of node 2's
            # filter still says maybe-present (stale) — repair must
            # STILL push it (positives are probed, not trusted)
            lost = m.chunks[0].digest
            assert nodes[2].store.chunks.delete(lost)
            assert not nodes[2].store.chunks.has(lost)
            await nodes[1].repair_once()
            assert nodes[2].store.chunks.has(lost)
            assert nodes[1].index_stats()["filterFp"] >= 1
        finally:
            await _stop_all(nodes)

    asyncio.run(run())


def test_wal_replay_never_overwrites_pre_open_notes(tmp_path):
    """WAL records are strictly OLDER than anything noted in this
    life: a delete recorded before open() (the boot-sweep shape) must
    not be resurrected by the previous life's replayed put record."""
    idx = DigestIndex(tmp_path / "ix", memtable_entries=4096)
    idx.open_or_rebuild(lambda: [])
    d = sha256_hex(b"phantom")
    idx.note_put(d)
    idx.close()                      # the put record is in the WAL
    idx2 = DigestIndex(tmp_path / "ix", memtable_entries=4096)
    idx2.note_delete(d)              # noted BEFORE open
    idx2.open_or_rebuild(lambda: [])
    assert not idx2.lookup(d)
    idx2.close()


def test_boot_sweep_orphans_not_resurrected_by_index(tmp_path):
    """End to end: an aged orphan chunk swept at boot must be ABSENT
    from the index afterwards — the index opens before the sweep, so
    the sweep's deletes are recorded on a live index instead of being
    overwritten by the WAL replay (the phantom the code review's repro
    demonstrated: has_chunks answering 'have' for swept bytes)."""
    ix = IndexConfig(enabled=True, filter_sync_s=0)

    async def run() -> None:
        cluster = _mk_cluster(1, rf=1)
        nodes = await _start_nodes(cluster, tmp_path, index=ix)
        node = nodes[1]
        payload = b"orphan-chunk" * 800
        d = sha256_hex(payload)
        await node.cas.put(d, payload)      # no manifest: an orphan
        old = time.time() - 7200            # past the 1 h GC grace
        os.utime(node.store.chunks._path(d), (old, old))
        await _stop_all(nodes)
        nodes = await _start_nodes(cluster, tmp_path, index=ix)
        node = nodes[1]
        try:
            assert not (node.store.root / "chunks" / d[:2] / d).exists()
            assert not node.index.lookup(d)   # no phantom
            assert not node.store.chunks.has(d)
        finally:
            await _stop_all(nodes)

    asyncio.run(run())


def test_doctor_index_stale_rule():
    """The doctor names a node whose peer-filter replicas stopped
    refreshing (>= 10x the sync cadence, 60 s floor) — and stays quiet
    for fresh replicas, disabled planes, and exchange-off nodes."""
    from dfs_tpu.obs.doctor import diagnose

    now = time.time()

    def snap(index) -> dict:
        return {"now": now, "receivedAt": now, "index": index}

    findings = diagnose(
        {1: snap({"enabled": True, "syncS": 1.0,
                  "peerAgeS": {"2": 300.0, "3": 2.0}}),
         2: snap({"enabled": True, "syncS": 1.0,
                  "peerAgeS": {"1": 3.0}}),
         3: snap({"enabled": False})}, coordinator_now=now)
    stale = [f for f in findings if f["rule"] == "index_stale"]
    assert len(stale) == 1 and stale[0]["peers"] == [1]
    assert "'2'" in stale[0]["evidence"]
    # exchange off (syncS 0) or fresh everywhere: no finding
    findings = diagnose(
        {1: snap({"enabled": True, "syncS": 0,
                  "peerAgeS": {"2": 9999.0}}),
         2: snap({"enabled": True, "syncS": 1.0,
                  "peerAgeS": {"1": 1.0}})}, coordinator_now=now)
    assert not [f for f in findings if f["rule"] == "index_stale"]


# ------------------------------------------------------------------ #
# bench smoke + schema lock
# ------------------------------------------------------------------ #

def test_bench_dedup_index_tiny_smoke(tmp_path):
    """``bench_dedup_index.py --tiny`` end to end: all four gate
    families must hold at tiny scale, and the JSON schema matches what
    the committed DEDUP_INDEX_r16.json embeds."""
    out_path = tmp_path / "ix_tiny.json"
    res = subprocess.run(
        [sys.executable, str(REPO / "bench_dedup_index.py"), "--tiny",
         "--out", str(out_path)],
        cwd=tmp_path, capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": str(REPO)})
    assert res.returncode == 0, (
        f"bench_dedup_index --tiny failed:\n{res.stdout[-2000:]}"
        f"\n{res.stderr[-4000:]}")
    out = json.loads(out_path.read_text())
    assert out["metric"] == "dedup_index_plane" and out["round"] == 16
    assert out["ok"] is True
    g = out["gates"]
    assert g["memory"]["ok"] and g["memory"]["bytesPerChunk"] <= 32.0
    assert g["probe_reduction"]["ok"]
    assert g["probe_reduction"]["reductionPct"] >= 80.0
    assert g["dedup_preserved"]["ok"]
    assert g["dedup_preserved"]["storedBytesIndexOn"] \
        == g["dedup_preserved"]["storedBytesIndexOff"]
    assert g["crash_mid_compaction"]["ok"]
    assert g["crash_mid_compaction"]["ackedFilesIntact"]
    assert g["crash_mid_compaction"]["indexMatchesWalk"]


def test_lsi_open_info_runs_count_reported_under_lock(tmp_path):
    """r17 DFS008 regression: open_or_rebuild's run-list length moved
    under the store lock (nothing pins the open to run before workers
    start); the reported count must still match the persisted runs."""
    idx = DigestIndex(tmp_path / "ix", memtable_entries=256,
                      compact_runs=64)
    idx.open_or_rebuild(lambda: [])
    for d in _digests(600, "r"):
        idx.note_put(d)            # memtable flushes => persisted runs
    idx.close()
    idx2 = DigestIndex(tmp_path / "ix", memtable_entries=256,
                       compact_runs=64)
    info = idx2.open_or_rebuild(lambda: [])
    cur = json.loads((tmp_path / "ix" / "CURRENT").read_bytes())
    assert info["rebuilt"] is False
    assert info["runs"] == len(cur["runs"]) and info["runs"] > 0
    idx2.close()
