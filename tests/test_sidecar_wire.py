"""The non-Python host boundary, proven: a C++ client with NO gRPC (or
any HTTP/2) library — POSIX sockets + the documented wire contract only
(docs/sidecar_wire.md, dfs_tpu/native/sidecar_client.cpp) — streams a
file into a LIVE dfs.Sidecar and gets back a chunk table that must
match the CPU oracle fragmenter byte for byte.

This is the conformance test for the wire spec: it exercises the
h2c preface, SETTINGS exchange, static-table HPACK request headers,
both flow-control windows (the payload exceeds the 64 KiB initial
windows many times over), gRPC length-prefixed framing, and the JSON
response — everything a foreign StorageNode implementation needs."""

import json
import shutil
import subprocess

import numpy as np
import pytest

from dfs_tpu.native import build_sidecar_client

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain")


@pytest.fixture(scope="module")
def sidecar():
    from dfs_tpu.sidecar.service import SidecarServer

    srv = SidecarServer(port=0, fragmenter="cdc-anchored")
    srv.start()
    yield srv
    srv.stop()


def test_cpp_client_chunk_table_matches_oracle(tmp_path, rng, sidecar):
    binary = build_sidecar_client()
    assert binary is not None, "g++ present but the client failed to build"

    data = rng.integers(0, 256, size=3_000_000, dtype=np.uint8).tobytes()
    payload = tmp_path / "payload.bin"
    payload.write_bytes(data)

    out = subprocess.run(
        [str(binary), "127.0.0.1", str(sidecar.port), str(payload)],
        capture_output=True, timeout=120)
    assert out.returncode == 0, out.stderr.decode()
    table = json.loads(out.stdout)

    want = sidecar.fragmenter.chunk(data)
    assert table["size"] == len(data)
    assert table["fragmenter"] == "cdc-anchored"
    assert len(table["chunks"]) == len(want)
    for got, ref in zip(table["chunks"], want):
        assert (got["offset"], got["length"], got["digest"]) \
            == (ref.offset, ref.length, ref.digest)


def test_cpp_client_empty_file(tmp_path, sidecar):
    binary = build_sidecar_client()
    assert binary is not None

    payload = tmp_path / "empty.bin"
    payload.write_bytes(b"")
    out = subprocess.run(
        [str(binary), "127.0.0.1", str(sidecar.port), str(payload)],
        capture_output=True, timeout=120)
    assert out.returncode == 0, out.stderr.decode()
    table = json.loads(out.stdout)
    assert table["size"] == 0 and table["chunks"] == []


def test_cpp_client_health_and_unary_methods(tmp_path, rng, sidecar):
    """The other documented methods through the same library-less
    client: Health (empty message -> JSON status incl. the stream_span
    'window' bound) and unary ChunkHash (whole payload in one gRPC
    message, table identical to the streamed path)."""
    binary = build_sidecar_client()
    assert binary is not None

    data = rng.integers(0, 256, size=300_000, dtype=np.uint8).tobytes()
    payload = tmp_path / "p.bin"
    payload.write_bytes(data)

    out = subprocess.run(
        [str(binary), "127.0.0.1", str(sidecar.port), str(payload),
         "Health"], capture_output=True, timeout=120)
    assert out.returncode == 0, out.stderr.decode()
    health = json.loads(out.stdout)
    assert health["ok"] is True
    assert health["fragmenter"] == "cdc-anchored"
    assert health["window"] == (sidecar.fragmenter.stream_span() or 0)

    out = subprocess.run(
        [str(binary), "127.0.0.1", str(sidecar.port), str(payload),
         "ChunkHash"], capture_output=True, timeout=120)
    assert out.returncode == 0, out.stderr.decode()
    table = json.loads(out.stdout)
    want = sidecar.fragmenter.chunk(data)
    assert [(g["offset"], g["length"], g["digest"])
            for g in table["chunks"]] \
        == [(r.offset, r.length, r.digest) for r in want]


def test_cpp_client_duplex_streams_batches(tmp_path, rng, sidecar):
    """ChunkHashDuplex from the library-less client — the method a
    teeing storage node actually uses, with the deadlock-relevant
    window rule: the client first fetches Health's reporting-lag
    ``window`` and never lets more than 2x that many un-reported bytes
    into flight, exactly like SidecarFragmenter.chunks_stream. If the
    sidecar's real lag exceeded its advertised bound, this client
    would stall at the cap and die on its 60 s socket timeout — so a
    green run IS the conformance proof for the window contract. Output
    is JSONL: chunk batches as the walk finalizes them, then the done
    message; merged chunks must match the CPU oracle byte for byte."""
    binary = build_sidecar_client()
    assert binary is not None

    data = rng.integers(0, 256, size=3_000_000, dtype=np.uint8).tobytes()
    payload = tmp_path / "dup.bin"
    payload.write_bytes(data)

    out = subprocess.run(
        [str(binary), "127.0.0.1", str(sidecar.port), str(payload),
         "ChunkHashDuplex"], capture_output=True, timeout=120)
    assert out.returncode == 0, out.stderr.decode()
    lines = [json.loads(ln) for ln in out.stdout.splitlines() if ln.strip()]
    assert len(lines) >= 2, "expected streamed batches plus a done message"
    *batches, done = lines
    assert done["done"] is True and done["size"] == len(data)
    assert all("chunks" in b for b in batches)
    # the window bound must be real for the cap to have been exercised
    assert (sidecar.fragmenter.stream_span() or 0) > 0
    merged = [c for b in batches for c in b["chunks"]]
    want = sidecar.fragmenter.chunk(data)
    assert [(g["offset"], g["length"], g["digest"]) for g in merged] \
        == [(r.offset, r.length, r.digest) for r in want]
    # file id in the done message matches the digest-derived id
    from dfs_tpu.ops.cdc_v2 import file_id_from_digests
    assert done["fileId"] == file_id_from_digests(
        [r.digest for r in want])
