"""Overload-survival plane (r18): end-to-end deadlines, hedged reads,
and the overload/compound-fault bench (docs/serve.md, docs/chaos.md).

Layers of coverage:

- UNIT: the deadline contextvar (activation, expiry, header/wire
  parsing, task inheritance), the hedge policy (delay clamp + token
  bucket + recency windows), the harness Retry-After decorrelated
  jitter, and the doctor's hedge_storm rule.
- DEFAULT-OFF IDENTITY: no X-Dfs-Deadline header + default config =
  no deadline context, no `deadline` wire field, no hedge policy —
  the pre-r18 read/write paths byte-identical (the chaos/index-plane
  discipline).
- ADMISSION: a request arriving expired sheds at the gate (counted
  ``deadlineShed``, never plain ``shed``); a QUEUED waiter is evicted
  the moment its deadline passes; a queued waiter whose client hangs
  up frees its position and never consumes a slot at the head (the
  r18 disconnect satellite's regression).
- RPC + DISPATCH: the client refuses to send (and to keep retrying)
  expired work; ``_dispatch`` refuses it server-side before any CAS
  touch — with the counter/journal evidence the bench gates on.
- HEDGED READS: a 3-node in-process cluster with one slow replica —
  the hedge fires, the backup wins, the read returns fast, and the
  journal carries hedge_fired/hedge_won.
- The ``bench_overload.py --tiny`` subprocess smoke gating all five
  scripted scenarios end to end + the OVERLOAD_r18.json schema lock.
"""

from __future__ import annotations

import asyncio
import json
import os
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

from dfs_tpu.comm.rpc import DeadlineExpired, InternalClient
from dfs_tpu.config import (CDCParams, CensusConfig, ChaosConfig,
                            ClusterConfig, NodeConfig, PeerAddr,
                            ServeConfig)
from dfs_tpu.node.runtime import StorageNodeServer
from dfs_tpu.obs.doctor import diagnose
from dfs_tpu.serve.admission import (AdmissionGate, ClientDisconnected,
                                     ShedError)
from dfs_tpu.serve.hedge import HedgePolicy
from dfs_tpu.utils import deadline

REPO = Path(__file__).resolve().parent.parent
CDC = CDCParams(min_size=2048, avg_size=8192, max_size=65536)
CENSUS_OFF = CensusConfig(history_interval_s=0)


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _mk_cluster(n: int, rf: int) -> ClusterConfig:
    ports = _free_ports(2 * n)
    peers = tuple(PeerAddr(node_id=i + 1, host="127.0.0.1",
                           port=ports[2 * i],
                           internal_port=ports[2 * i + 1])
                  for i in range(n))
    return ClusterConfig(peers=peers, replication_factor=rf)


async def _start_nodes(cluster: ClusterConfig, root: Path,
                       overrides: dict[int, dict] | None = None
                       ) -> dict[int, StorageNodeServer]:
    nodes = {}
    for p in cluster.peers:
        kw = dict((overrides or {}).get(p.node_id, {}))
        cfg = NodeConfig(node_id=p.node_id, cluster=cluster,
                         data_root=root, fragmenter="cdc", cdc=CDC,
                         health_probe_s=0, census=CENSUS_OFF, **kw)
        n = StorageNodeServer(cfg)
        await n.start()
        nodes[p.node_id] = n
    return nodes


async def _stop_all(nodes) -> None:
    for n in nodes.values():
        await n.stop()


# ------------------------------------------------------------------ #
# unit: deadline contextvar
# ------------------------------------------------------------------ #

def test_deadline_context_basics():
    assert deadline.remaining() is None
    assert not deadline.expired()
    tok = deadline.activate(30.0)
    try:
        rem = deadline.remaining()
        assert rem is not None and 29.0 < rem <= 30.0
        assert not deadline.expired()
    finally:
        deadline.restore(tok)
    assert deadline.remaining() is None
    # non-positive budget activates ALREADY expired (the drop paths
    # are exactly what must fire for a dead-on-arrival request)
    tok = deadline.activate(-1.0)
    try:
        assert deadline.expired()
    finally:
        deadline.restore(tok)
    # absurd budgets are clamped
    tok = deadline.activate(10 ** 9)
    try:
        assert deadline.remaining() <= deadline.MAX_DEADLINE_S
    finally:
        deadline.restore(tok)


def test_deadline_header_and_wire_parsing():
    assert deadline.parse_header("2.5") == 2.5
    assert deadline.parse_header(" 0.25 ") == 0.25
    assert deadline.parse_header(None) is None
    assert deadline.parse_header("") is None
    assert deadline.parse_header("soon") is None
    assert deadline.parse_header("inf") is None
    assert deadline.parse_wire(1.5) == 1.5
    assert deadline.parse_wire(2) == 2.0
    assert deadline.parse_wire(None) is None
    assert deadline.parse_wire("1.5") is None
    assert deadline.parse_wire(True) is None
    assert deadline.parse_wire(float("nan")) is None


def test_deadline_inherited_by_tasks_and_threads():
    async def run() -> None:
        tok = deadline.activate(60.0)
        try:
            async def child() -> float | None:
                return deadline.remaining()

            got = await asyncio.create_task(child())
            assert got is not None and got > 50.0
            got = await asyncio.to_thread(deadline.remaining)
            assert got is not None and got > 50.0
        finally:
            deadline.restore(tok)

    asyncio.run(run())


# ------------------------------------------------------------------ #
# unit: hedge policy
# ------------------------------------------------------------------ #

def test_hedge_policy_delay_clamp():
    h = HedgePolicy(floor_s=0.05, cap_s=0.5, budget_per_s=10.0)
    assert h.delay_s(None) == 0.05            # no sample: floor
    assert h.delay_s(0.001) == 0.05           # below floor: floor
    assert h.delay_s(0.06) == pytest.approx(0.18)   # 3x mean
    assert h.delay_s(10.0) == 0.5             # above cap: cap


def test_hedge_policy_token_bucket_and_windows():
    h = HedgePolicy(floor_s=0.0, cap_s=1.0, budget_per_s=0.0)
    h._tokens = 2.0
    assert h.take() and h.take()
    assert not h.take()                       # empty, no refill
    assert h.denied == 1
    h.note_fired()
    h.note_fired()
    h.note_won()
    s = h.stats()
    assert s["fired"] == 2 and s["won"] == 1 and s["denied"] == 1
    assert s["firedRecent"] == 2 and s["deniedRecent"] == 1
    # refill restores tokens over time
    h2 = HedgePolicy(floor_s=0.0, cap_s=1.0, budget_per_s=1000.0)
    while h2.take():
        pass
    time.sleep(0.01)                          # ~10 tokens of refill
    assert h2.take()


def test_serve_config_validates_deadline_hedge_fields():
    with pytest.raises(ValueError):
        ServeConfig(default_deadline_s=-1)
    with pytest.raises(ValueError):
        ServeConfig(hedge_floor_s=0.5, hedge_cap_s=0.1)
    with pytest.raises(ValueError):
        ServeConfig(hedge_budget_per_s=-1)
    # hedge master switch: no budget, no policy
    from dfs_tpu.serve import ServingTier

    tier = ServingTier(ServeConfig())
    assert tier.hedge is None
    assert tier.stats()["hedge"]["enabled"] is False
    assert tier.stats()["defaultDeadlineS"] == 0.0
    tier_on = ServingTier(ServeConfig(hedge_budget_per_s=5.0))
    assert tier_on.hedge is not None
    assert tier_on.stats()["hedge"]["enabled"] is True


# ------------------------------------------------------------------ #
# unit: harness Retry-After decorrelated jitter
# ------------------------------------------------------------------ #

def test_loadgen_honors_retry_after_with_jitter(tmp_path):
    """A 503 with Retry-After is retried AFTER a decorrelated-jitter
    sleep bounded below by the advertised budget — never immediately
    (the retry-storm regression this satellite fixes)."""
    from scripts.chaos_harness import ClusterHarness, LoadGen

    h = ClusterHarness(1, tmp_path, chaos=False)
    answers = [(503, b"busy", {"retry-after": "2"}),
               (503, b"busy", {"retry-after": "2"}),
               (201, json.dumps({"fileId": "x"}).encode(), {})]
    calls: list = []

    def fake_http_h(node, method, path, body=None, headers=None,
                    timeout=60.0):
        calls.append(path)
        return answers[min(len(calls) - 1, len(answers) - 1)]

    h.http_h = fake_http_h
    load = LoadGen(h, payload_bytes=64, retry_503=2)
    sleeps: list[float] = []
    load._sleep = sleeps.append
    status, _ = load._request_with_503_retry(1, "POST", "/upload")
    assert status == 201
    assert len(calls) == 3 and len(sleeps) == 2
    # sleep 1: uniform(retry_after, 3*retry_after) — never below the
    # advertised budget, never an immediate retry
    assert 2.0 <= sleeps[0] <= 6.0
    # sleep 2 decorrelates off sleep 1 (uniform(base, 3*prev), capped)
    assert 2.0 <= sleeps[1] <= min(10.0, 3.0 * sleeps[0])
    assert load.snapshot()["retries_503"] == 2
    # retries exhausted: the final 503 is returned, not retried forever
    calls.clear()
    sleeps.clear()
    answers[:] = [(503, b"busy", {"retry-after": "1"})] * 5
    status, _ = load._request_with_503_retry(1, "GET", "/download")
    assert status == 503 and len(calls) == 3 and len(sleeps) == 2


# ------------------------------------------------------------------ #
# unit: doctor hedge_storm rule
# ------------------------------------------------------------------ #

def _snap(nid: int, hedge: dict | None) -> dict:
    return {"nodeId": nid, "now": time.time(),
            "hedge": hedge if hedge is not None else {"enabled": False}}


def test_doctor_hedge_storm_rule():
    now = time.time()
    # sustained at-refill hedging -> storm
    sick = {1: _snap(1, {"enabled": True, "budgetPerS": 0.5,
                         "firedRecent": 30, "deniedRecent": 0}),
            2: _snap(2, None)}
    for s in sick.values():
        s["receivedAt"] = now
    findings = diagnose(sick, coordinator_now=now)
    rules = [f["rule"] for f in findings]
    assert "hedge_storm" in rules
    f = next(f for f in findings if f["rule"] == "hedge_storm")
    assert f["peers"] == [1]
    # SUSTAINED denials count as storm evidence even below the
    # refill-rate bar; a single blip's denial (the plane absorbing a
    # burst as designed) does not
    denied = {1: _snap(1, {"enabled": True, "budgetPerS": 5.0,
                           "firedRecent": 10, "deniedRecent": 9,
                           "receivedAt": now})}
    assert any(f["rule"] == "hedge_storm"
               for f in diagnose(denied, coordinator_now=now))
    blip = {1: _snap(1, {"enabled": True, "budgetPerS": 5.0,
                         "firedRecent": 10, "deniedRecent": 1,
                         "receivedAt": now})}
    assert not any(f["rule"] == "hedge_storm"
                   for f in diagnose(blip, coordinator_now=now))
    # a handful of hedges is the plane WORKING, not a storm
    quiet = {1: _snap(1, {"enabled": True, "budgetPerS": 0.05,
                          "firedRecent": 3, "deniedRecent": 0,
                          "receivedAt": now})}
    assert not any(f["rule"] == "hedge_storm"
                   for f in diagnose(quiet, coordinator_now=now))
    # malformed cross-version fields cost nothing
    bad = {1: _snap(1, {"enabled": True, "budgetPerS": "lots",
                        "firedRecent": "many", "receivedAt": now})}
    assert not any(f["rule"] == "hedge_storm"
                   for f in diagnose(bad, coordinator_now=now))
    # a generous budget's at-refill bar clamps to the producer's
    # bounded window (hedge.py windowCap): a SATURATED window is a
    # storm even though refill*60 (=1200 here) is a count the 512-cap
    # deque can never show — without the clamp the rule was dead code
    # exactly for generous budgets (r18 review finding)
    saturated = {1: _snap(1, {"enabled": True, "budgetPerS": 20.0,
                              "firedRecent": 512, "deniedRecent": 0,
                              "windowCap": 512, "receivedAt": now})}
    assert any(f["rule"] == "hedge_storm"
               for f in diagnose(saturated, coordinator_now=now))


# ------------------------------------------------------------------ #
# admission: deadline eviction + disconnect
# ------------------------------------------------------------------ #

def test_gate_sheds_expired_on_arrival_counted_separately():
    async def run() -> None:
        gate = AdmissionGate("download", slots=2, queue_depth=4)
        tok = deadline.activate(-1.0)
        try:
            with pytest.raises(ShedError):
                await gate.acquire()
        finally:
            deadline.restore(tok)
        s = gate.stats()
        assert s["deadlineShed"] == 1
        assert s["shed"] == 0          # NOT a capacity shed
        assert s["active"] == 0        # no slot consumed
        # without a deadline the gate admits normally
        await gate.acquire()
        assert gate.stats()["active"] == 1
        gate.release()

    asyncio.run(run())


def test_gate_evicts_queued_waiter_on_deadline_expiry():
    async def run() -> None:
        gate = AdmissionGate("download", slots=1, queue_depth=4)
        await gate.acquire()               # hold the only slot
        tok = deadline.activate(0.05)
        try:
            t0 = time.monotonic()
            with pytest.raises(ShedError):
                await gate.acquire()
            took = time.monotonic() - t0
            assert took < 2.0              # evicted AT expiry, not at
            # slot-release time (the holder never releases here)
        finally:
            deadline.restore(tok)
        s = gate.stats()
        assert s["deadlineShed"] == 1 and s["waiting"] == 0
        # the slot is intact: release hands it to a live waiter
        waiter = asyncio.create_task(gate.acquire())
        await asyncio.sleep(0.01)
        gate.release()
        await asyncio.wait_for(waiter, timeout=2)
        assert gate.stats()["active"] == 1
        gate.release()
        assert gate.stats()["active"] == 0

    asyncio.run(run())


def test_gate_frees_slot_of_hung_up_queued_waiter():
    """THE disconnect regression: a queued download whose client hangs
    up must free its queue position — when the head of the queue is
    reached the slot passes to a LIVE waiter, and the dead request
    never holds it."""

    async def run() -> None:
        gate = AdmissionGate("download", slots=1, queue_depth=8)
        await gate.acquire()               # hold the only slot
        gone = asyncio.get_running_loop().create_future()

        async def disconnected():
            return await gone              # resolves to b"" = EOF

        dead = asyncio.create_task(gate.acquire(
            disconnected=lambda: disconnected()))
        await asyncio.sleep(0.01)
        live = asyncio.create_task(gate.acquire())   # queued behind it
        await asyncio.sleep(0.01)
        assert gate.stats()["waiting"] == 2
        gone.set_result(b"")               # the dead client hangs up
        with pytest.raises(ClientDisconnected):
            await dead
        assert gate.stats()["disconnects"] == 1
        assert gate.stats()["waiting"] == 1
        # slot release skips the ghost and admits the live waiter
        gate.release()
        await asyncio.wait_for(live, timeout=2)
        assert gate.stats()["active"] == 1
        gate.release()
        assert gate.stats()["active"] == 0
        # stray non-EOF bytes are NOT a hangup: the waiter stays
        # queued and the watcher RE-ARMS (a one-shot watcher would go
        # blind after the first byte)
        await gate.acquire()
        calls: list[int] = []

        async def noisy():
            calls.append(1)
            if len(calls) == 1:
                return b"x"            # a pipelined stray byte
            # then quiet: a watcher that never resolves again
            return await asyncio.get_running_loop().create_future()

        waiter = asyncio.create_task(gate.acquire(
            disconnected=lambda: noisy()))
        await asyncio.sleep(0.01)
        assert not waiter.done()
        assert len(calls) >= 2         # re-armed after the stray byte
        gate.release()
        await asyncio.wait_for(waiter, timeout=2)
        gate.release()
        # stray byte FOLLOWED by a real EOF: the re-armed watcher must
        # still catch the hangup (one-shot disarming missed exactly
        # this — the dead request consumed a slot at the head)
        await gate.acquire()
        seq = [b"x", b""]

        async def stray_then_eof():
            if seq:
                return seq.pop(0)
            return await asyncio.get_running_loop().create_future()

        dead2 = asyncio.create_task(gate.acquire(
            disconnected=lambda: stray_then_eof()))
        with pytest.raises(ClientDisconnected):
            await dead2
        assert gate.stats()["disconnects"] == 2
        gate.release()
        assert gate.stats()["active"] == 0

    asyncio.run(run())


# ------------------------------------------------------------------ #
# RPC client + dispatch: expired work never runs
# ------------------------------------------------------------------ #

def test_rpc_client_refuses_expired_work():
    async def run() -> None:
        client = InternalClient()
        called = []

        async def boom(*a, **kw):
            called.append(1)
            raise AssertionError("expired call must never reach the "
                                 "wire")

        client._request = boom
        peer = PeerAddr(node_id=2, host="127.0.0.1", port=1,
                        internal_port=1)
        tok = deadline.activate(-1.0)
        try:
            with pytest.raises(DeadlineExpired):
                await client.call(peer, {"op": "health"})
        finally:
            deadline.restore(tok)
        assert not called

    asyncio.run(run())


def test_rpc_client_stops_retrying_when_budget_cannot_cover():
    """First attempt fails at the transport; the remaining deadline
    cannot cover backoff + connect — the client gives up with
    DeadlineExpired instead of burning retries on a dead caller."""

    async def run() -> None:
        client = InternalClient(connect_timeout_s=2.0, retries=3)
        attempts = []

        async def fail_once(peer, header, body, timeout_s=None,
                            acct=None):
            attempts.append(1)
            raise ConnectionRefusedError("nope")

        client._call_once = fail_once
        peer = PeerAddr(node_id=2, host="127.0.0.1", port=1,
                        internal_port=1)
        tok = deadline.activate(0.5)    # < backoff + connect_timeout
        try:
            with pytest.raises(DeadlineExpired):
                await client.call(peer, {"op": "health"})
        finally:
            deadline.restore(tok)
        assert len(attempts) == 1       # no second attempt
        # without a deadline the same failure retries the full envelope
        with pytest.raises(Exception) as ei:
            await client.call(peer, {"op": "health"})
        assert "unreachable" in str(ei.value)
        assert len(attempts) == 1 + client.retries

    asyncio.run(run())


def test_dispatch_drops_expired_and_wire_carries_remaining(tmp_path):
    async def run() -> None:
        cluster = _mk_cluster(2, rf=2)
        nodes = await _start_nodes(cluster, tmp_path)
        try:
            n1, n2 = nodes[1], nodes[2]
            # live deadline rides the wire and the op is served
            tok = deadline.activate(30.0)
            try:
                resp, _ = await n1.client.call(cluster.peer(2),
                                               {"op": "health"})
                assert resp["ok"]
            finally:
                deadline.restore(tok)
            # expired context server-side: _dispatch refuses before any
            # CAS touch, with the counter + journal evidence
            tok = deadline.activate(0.000001)
            await asyncio.sleep(0.002)
            try:
                resp, _ = await n2._dispatch({"op": "get_chunk",
                                              "digest": "0" * 64}, b"")
            finally:
                deadline.restore(tok)
            assert resp["ok"] is False
            assert "deadline" in resp["error"]
            assert n2.counters.snapshot()["deadline_drops"] >= 1
            # the journal writes on its own thread; wait for the emit
            # to reach disk before reading the file back
            await asyncio.to_thread(n2.obs.journal.flush)
            tail = await asyncio.to_thread(n2.obs.journal.tail, 0.0,
                                           256)
            assert any(e.get("type") == "deadline_shed"
                       for e in tail["events"])
            # DEFAULT-OFF IDENTITY: no deadline context -> no wire
            # field, full service (the pre-r18 header exactly)
            sent: list[dict] = []
            real = n1.client._call_once

            async def spy(peer, header, body, timeout_s=None,
                          acct=None):
                sent.append(dict(header))
                return await real(peer, header, body, timeout_s, acct)

            n1.client._call_once = spy
            resp, _ = await n1.client.call(cluster.peer(2),
                                           {"op": "health"})
            assert resp["ok"]
            assert "deadline" not in sent[-1]
            n1.client._call_once = real
        finally:
            await _stop_all(nodes)

    asyncio.run(run())


def test_http_deadline_header_sheds_expired_request(tmp_path):
    """The HTTP edge births the deadline; an expired budget is shed at
    the download gate as a 503 (deadlineShed), and the downloads
    counter proves the read path never ran. Absent header + default
    config = no deadline at all."""

    async def run() -> None:
        cluster = _mk_cluster(1, rf=1)
        nodes = await _start_nodes(
            cluster, tmp_path,
            overrides={1: {"serve": ServeConfig(download_slots=2)}})
        node = nodes[1]
        try:
            data = os.urandom(30000)
            m, _ = await node.upload(data, "f.bin")
            addr = cluster.peer(1)

            async def http(path: str, extra: str = "") -> bytes:
                reader, writer = await asyncio.open_connection(
                    addr.host, addr.port)
                writer.write((f"GET {path} HTTP/1.1\r\n"
                              f"Host: x\r\n{extra}"
                              "Connection: close\r\n\r\n").encode())
                await writer.drain()
                out = await reader.read(-1)
                writer.close()
                return out

            before = node.counters.snapshot().get("downloads", 0)
            out = await http(f"/download?fileId={m.file_id}",
                             "X-Dfs-Deadline: 0.000001\r\n")
            assert out.startswith(b"HTTP/1.1 503")
            assert b"Retry-After" in out
            adm = node.serve.admission.download.stats()
            assert adm["deadlineShed"] == 1 and adm["shed"] == 0
            assert node.counters.snapshot().get("downloads", 0) \
                == before
            # no header: served in full, byte-identical
            out = await http(f"/download?fileId={m.file_id}")
            assert out.startswith(b"HTTP/1.1 200")
            assert out.endswith(data)
            # malformed header: ignored, never an error
            out = await http(f"/download?fileId={m.file_id}",
                             "X-Dfs-Deadline: soon\r\n")
            assert out.startswith(b"HTTP/1.1 200")
        finally:
            await _stop_all(nodes)

    asyncio.run(run())


# ------------------------------------------------------------------ #
# hedged reads on a live in-process cluster
# ------------------------------------------------------------------ #

def test_hedged_read_beats_slow_replica(tmp_path):
    """3-node rf=2 cluster, node 3 serving every inbound op 250 ms
    late: node 2's remote digests are the {3,1}-owned ones (primary
    node 3), so an unhedged read from node 2 eats the delay while the
    hedged read races node 1 and wins fast — with the
    hedge_fired/hedge_won journal + counter evidence."""

    async def run() -> None:
        cluster = _mk_cluster(3, rf=2)
        hedged = ServeConfig(hedge_budget_per_s=50.0,
                             hedge_floor_s=0.05, hedge_cap_s=0.3)
        nodes = await _start_nodes(
            cluster, tmp_path,
            overrides={2: {"serve": hedged},
                       3: {"chaos": ChaosConfig(enabled=True)}})
        try:
            # ~25 chunks: the odds that NONE lands in the {3,1} owner
            # set (i.e. node 2 never routes a fetch at node 3 and no
            # hedge can fire) are (2/3)^25 ~ 4e-5 — a 60 KB corpus
            # flaked on exactly that
            data = os.urandom(200000)
            m, _ = await nodes[1].upload(data, "t.bin")
            # healthy warm read (seeds the windowed means)
            _, body = await nodes[2].download(m.file_id)
            assert bytes(body) == data
            nodes[3].chaos.set(serve_delay_s=0.25)
            lats = []
            for _ in range(3):
                t0 = time.monotonic()
                _, body = await nodes[2].download(m.file_id)
                assert bytes(body) == data
                lats.append(time.monotonic() - t0)
            hs = nodes[2].serve.hedge.stats()
            assert hs["fired"] >= 1 and hs["won"] >= 1
            # the hedge must beat the injected delay by a wide margin
            # (~55 ms observed vs 250+ ms unhedged); 0.2 s keeps the
            # assertion robust on a loaded host
            assert min(lats) < 0.2, lats
            await asyncio.to_thread(nodes[2].obs.journal.flush)
            tail = await asyncio.to_thread(nodes[2].obs.journal.tail,
                                           0.0, 512)
            kinds = {e.get("type") for e in tail["events"]}
            assert "hedge_fired" in kinds and "hedge_won" in kinds
            nodes[3].chaos.set(serve_delay_s=0.0)
            # default-off identity: the un-hedged nodes built no policy
            assert nodes[1].serve.hedge is None
            assert nodes[3].serve.hedge is None
        finally:
            await _stop_all(nodes)

    asyncio.run(run())


def test_hedge_budget_empty_waits_primary_out(tmp_path):
    """An exhausted hedge budget must mean NO second RPC — the read
    waits the slow primary out (hedging can never double load)."""

    async def run() -> None:
        cluster = _mk_cluster(3, rf=2)
        hedged = ServeConfig(hedge_budget_per_s=0.000001,
                             hedge_floor_s=0.01, hedge_cap_s=0.1)
        nodes = await _start_nodes(
            cluster, tmp_path,
            overrides={2: {"serve": hedged},
                       3: {"chaos": ChaosConfig(enabled=True)}})
        try:
            # ~25 chunks, like the sibling test: the denial needs at
            # least one {3,1}-owned digest so a hedge is WANTED —
            # a 40 KB corpus flaked on none existing (~20% of runs)
            data = os.urandom(200000)
            m, _ = await nodes[1].upload(data, "t.bin")
            hedge = nodes[2].serve.hedge
            hedge._tokens = 0.0            # bucket drained
            nodes[3].chaos.set(serve_delay_s=0.2)
            _, body = await nodes[2].download(m.file_id)
            assert bytes(body) == data     # correct, just slow
            hs = hedge.stats()
            assert hs["fired"] == 0 and hs["denied"] >= 1
            nodes[3].chaos.set(serve_delay_s=0.0)
        finally:
            await _stop_all(nodes)

    asyncio.run(run())


def test_streamed_download_stops_at_mid_stream_deadline_expiry(tmp_path):
    """The deadline must keep counting THROUGH a streamed body: the
    HTTP edge deliberately leaves the context armed for the handler's
    body iteration (r18 review finding — restoring it at the response
    head silently disarmed every batch after the first), so a
    mid-download expiry truncates the stream instead of fetching the
    remaining batches for a caller that gave up."""

    async def run() -> None:
        cluster = _mk_cluster(1, rf=1)
        nodes = await _start_nodes(
            cluster, tmp_path,
            overrides={1: {"chaos": ChaosConfig(enabled=True)}})
        node = nodes[1]
        try:
            node._FETCH_BATCH_BYTES = 8192   # many tiny batches
            # geometry chosen so the outcome is deterministic at BOTH
            # extremes of CDC chunking variance: >= 16 batches minimum
            # (1 MB / 64 KiB max chunk) x 50 ms/batch = > 0.8 s total,
            # so a 0.5 s deadline can never serve the full body; and
            # batch 0 costs at most ~5 chunk reads x 50 ms ~ 0.25 s,
            # so the head always commits first (a 120 KB corpus flaked
            # both ways on chunk-count luck)
            data = os.urandom(1_000_000)
            m, _ = await node.upload(data, "f.bin")
            # slow disk makes each batch cost ~50 ms SERVER-side, so
            # the deadline expires mid-stream regardless of how fast
            # the client drains the socket
            node.chaos.set(disk_delay_s=0.05)
            addr = cluster.peer(1)
            reader, writer = await asyncio.open_connection(
                addr.host, addr.port)
            writer.write((f"GET /download?fileId={m.file_id} "
                          "HTTP/1.1\r\nHost: x\r\n"
                          "X-Dfs-Deadline: 0.5\r\n"
                          "Connection: close\r\n\r\n").encode())
            await writer.drain()
            out = await reader.read(-1)
            writer.close()
            node.chaos.set(disk_delay_s=0.0)
            head, _, body = out.partition(b"\r\n\r\n")
            assert head.startswith(b"HTTP/1.1 200")   # head committed
            # before the expiry — truncation is the only honest signal
            assert len(body) < len(data), (
                "expired mid-stream but the full body was served")
            assert node.counters.snapshot()["deadline_drops"] >= 1
        finally:
            await _stop_all(nodes)

    asyncio.run(run())


def test_hedged_fetch_cancellation_reaps_racers(tmp_path):
    """A cancelled caller (client hung up mid-read) must take its
    in-flight hedge racers down with it — asyncio.shield/wait leave
    them running detached otherwise, still transferring bytes for a
    reader that is gone (r18 review finding)."""

    async def run() -> None:
        cluster = _mk_cluster(3, rf=2)
        hedged = ServeConfig(hedge_budget_per_s=50.0,
                             hedge_floor_s=0.05, hedge_cap_s=0.3)
        nodes = await _start_nodes(
            cluster, tmp_path,
            overrides={1: {"chaos": ChaosConfig(enabled=True)},
                       2: {"serve": hedged},
                       3: {"chaos": ChaosConfig(enabled=True)}})
        try:
            data = os.urandom(200000)
            m, _ = await nodes[1].upload(data, "t.bin")
            _, body = await nodes[2].download(m.file_id)   # warm
            # BOTH replicas slow: the hedge fires at ~50 ms and the
            # race then provably stays in flight past the cancel point
            # (a fast backup resolves it in ~60 ms total — the first
            # cut of this test cancelled a download that had already
            # finished)
            nodes[3].chaos.set(serve_delay_s=0.4)
            nodes[1].chaos.set(serve_delay_s=0.4)
            before = set(asyncio.all_tasks())
            dl = asyncio.create_task(nodes[2].download(m.file_id))
            await asyncio.sleep(0.15)   # hedge fired, both in flight
            dl.cancel()
            with pytest.raises(asyncio.CancelledError):
                await dl
            await asyncio.sleep(0.1)   # reaping settles
            # exclude the SERVER-side frame-service tasks: an
            # in-service op is deliberately never cancelled on peer
            # hangup (pre-r10 semantics, wire.py _on_broken) — they
            # finish their injected delay and fail at the reply write.
            # The CLIENT-side racers are what must not survive.
            leaked = [
                t for t in asyncio.all_tasks() - before
                if not t.done() and t is not asyncio.current_task()
                and t.get_coro().__qualname__
                != "FrameServerProtocol._serve"]
            assert not leaked, (
                f"cancelled download leaked racers: {leaked}")
            nodes[3].chaos.set(serve_delay_s=0.0)
            nodes[1].chaos.set(serve_delay_s=0.0)
        finally:
            await _stop_all(nodes)

    asyncio.run(run())


# ------------------------------------------------------------------ #
# the bench smoke + artifact schema lock
# ------------------------------------------------------------------ #

def test_bench_overload_tiny_smoke(tmp_path):
    """``bench_overload.py --tiny`` end to end: overload against armed
    gates (shed curve + Retry-After + goodput SLO + the deadline
    never-executed proof), compound faults, a membership change during
    a partition, EC reconstruction under a killed shard holder, and
    the hedged-read p99/RPC gates — all green, plus the
    OVERLOAD_r18.json schema lock against the committed artifact."""
    out_path = tmp_path / "overload_tiny.json"
    res = subprocess.run(
        [sys.executable, str(REPO / "bench_overload.py"), "--tiny",
         "--out", str(out_path)],
        cwd=tmp_path, capture_output=True, text=True, timeout=540,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": str(REPO)})
    os.sync()   # drain our writeback before the next test's fsyncs
    assert res.returncode == 0, (
        f"bench_overload --tiny failed:\n{res.stdout[-2000:]}"
        f"\n{res.stderr[-4000:]}")
    out = json.loads(out_path.read_text())
    assert out["metric"] == "overload_survival" and out["round"] == 18
    assert out["ok"] is True
    scenarios = out["scenarios"]
    assert set(scenarios) == {"overload", "compound", "ring_partition",
                              "ec_faults", "hedged_reads"}
    for name, s in scenarios.items():
        assert s["ok"] is True, name
    ov = scenarios["overload"]
    assert ov["shed_curve_engaged"] and ov["retry_after_present"]
    assert ov["zero_acked_loss"] and ov["byte_identical"]
    assert ov["goodput_within_slo"]
    assert ov["deadline_never_executed"]
    assert ov["offered_x_capacity"] == 5.0
    assert scenarios["compound"]["full_node_answers_507"]
    assert scenarios["compound"]["zero_acked_loss"]
    assert scenarios["ring_partition"]["epochs_converged"]
    assert scenarios["ec_faults"]["reconstruction_exercised"]
    assert scenarios["ec_faults"]["background_read_corruptions"] == 0
    hd = scenarios["hedged_reads"]
    assert hd["p99_cut_x"] >= 2.0 and hd["rpc_ratio"] <= 1.2
    assert hd["hedge_fired"] > 0 and hd["hedge_won"] > 0

    # schema lock against the COMMITTED artifact: same keys, so the
    # bench cannot drift away from what OVERLOAD_r18.json claims
    committed = json.loads((REPO / "OVERLOAD_r18.json").read_text())
    assert set(committed) == set(out)
    assert set(committed["scenarios"]) == set(out["scenarios"])
    for name in scenarios:
        assert set(committed["scenarios"][name]) \
            == set(out["scenarios"][name]), name
    assert committed["ok"] is True
