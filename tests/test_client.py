"""Smart client data plane (r19): edge CDC + dedup, direct-to-owner
striped transfers, single-hop ingest (docs/client.md).

Layers of coverage:

- UNIT: ClientConfig validation, the EchoCache (LRU bound, epoch
  invalidation, per-peer drop), and the client-side filter verdict
  (tri-state + the freshness bound that turns a stale replica into
  probes).
- IN-PROCESS CLUSTER: smart upload/download byte identity against
  real nodes, near-total dedup on re-upload, the stale/corrupt filter
  degrade (extra RPCs, never acked-byte loss or a wrong manifest),
  the legacy fallback matrix (old server / fallback=False), and the
  /commit endpoint's quorum re-count (dedup commit + 409 on absent
  chunks + heal of a below-quorum chunk).
- HEDGED WRITES (r18 leftover): a pulsing-slow replica makes the
  store-side hedge fire and win on the coordinator, with journal
  evidence — and the acked bytes read back from every node.
- BACKGROUND COMPACTION (r16 leftover): full compactions run on the
  dedicated thread, drain deterministically, and surface the stall
  attribution counters.
- The ``bench_client.py --tiny`` subprocess smoke (CLIENT_r19.json
  schema lock) rides tier-1.
"""

from __future__ import annotations

import asyncio
import json
import os
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

from dfs_tpu.client import SmartClient, SmartClientError
from dfs_tpu.config import (CDCParams, CensusConfig, ChaosConfig,
                            ClientConfig, ClusterConfig, IndexConfig,
                            NodeConfig, PeerAddr, ServeConfig)
from dfs_tpu.index import EchoCache
from dfs_tpu.index.filter import BlockedBloomFilter
from dfs_tpu.index.lsi import DigestIndex
from dfs_tpu.node.runtime import StorageNodeServer, UploadError
from dfs_tpu.utils.hashing import sha256_hex

REPO = Path(__file__).resolve().parent.parent
CDC = CDCParams(min_size=2048, avg_size=8192, max_size=65536)
CENSUS_OFF = CensusConfig(history_interval_s=0)


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _mk_cluster(n: int, rf: int) -> ClusterConfig:
    ports = _free_ports(2 * n)
    peers = tuple(PeerAddr(node_id=i + 1, host="127.0.0.1",
                           port=ports[2 * i],
                           internal_port=ports[2 * i + 1])
                  for i in range(n))
    return ClusterConfig(peers=peers, replication_factor=rf)


async def _start_nodes(cluster: ClusterConfig, root: Path,
                       index: IndexConfig | None = None,
                       overrides: dict[int, dict] | None = None
                       ) -> dict[int, StorageNodeServer]:
    nodes = {}
    for p in cluster.peers:
        kw = dict((overrides or {}).get(p.node_id, {}))
        cfg = NodeConfig(node_id=p.node_id, cluster=cluster,
                         data_root=root, fragmenter="cdc", cdc=CDC,
                         health_probe_s=0, census=CENSUS_OFF,
                         index=index or IndexConfig(), **kw)
        n = StorageNodeServer(cfg)
        await n.start()
        nodes[p.node_id] = n
    return nodes


async def _stop_all(nodes) -> None:
    for n in nodes.values():
        await n.stop()


def _smart(cluster: ClusterConfig, nid: int = 1,
           **cfg_kw) -> SmartClient:
    cfg_kw.setdefault("fallback", False)
    return SmartClient(host="127.0.0.1", port=cluster.peer(nid).port,
                       cfg=ClientConfig(**cfg_kw))


IX = IndexConfig(enabled=True, memtable_entries=1024, filter_sync_s=0)


# ------------------------------------------------------------------ #
# unit: config validation
# ------------------------------------------------------------------ #

def test_client_config_validates():
    c = ClientConfig()
    assert c.window == 2 and c.stripe == 4 and c.fallback
    for bad in (dict(window=0), dict(stripe=0),
                dict(hedge_budget_per_s=-1.0), dict(hedge_floor_s=-0.1),
                dict(hedge_cap_s=-1.0), dict(filter_max_age_s=-1.0),
                dict(echo_cache_entries=-1)):
        with pytest.raises(ValueError):
            ClientConfig(**bad)


# ------------------------------------------------------------------ #
# unit: echo-confirmed existence cache
# ------------------------------------------------------------------ #

def test_echo_cache_lru_bound_and_recency():
    c = EchoCache(per_peer=3)
    for d in ("d1", "d2", "d3"):
        c.confirm(7, d)
    assert c.confirmed(7, "d1")          # hit refreshes recency
    c.confirm(7, "d4")                   # evicts d2 (oldest untouched)
    assert not c.confirmed(7, "d2")
    assert c.confirmed(7, "d1") and c.confirmed(7, "d4")
    st = c.stats()
    assert st["perPeerCap"] == 3 and st["entries"] == 3
    assert st["hits"] >= 3 and st["confirms"] == 4


def test_echo_cache_epoch_change_invalidates_everything():
    c = EchoCache(per_peer=8)
    c.note_epoch(0)
    c.confirm(1, "a")
    c.confirm(2, "b")
    c.note_epoch(0)                      # same epoch: no-op
    assert c.confirmed(1, "a") and c.confirmed(2, "b")
    c.note_epoch(1)                      # ownership moved: all gone
    assert not c.confirmed(1, "a") and not c.confirmed(2, "b")
    assert c.stats()["invalidations"] == 1


def test_echo_cache_drop_is_per_peer():
    c = EchoCache(per_peer=8)
    c.confirm(1, "a")
    c.confirm(2, "b")
    c.drop(1)                            # peer 1 unreachable
    assert not c.confirmed(1, "a")
    assert c.confirmed(2, "b")


# ------------------------------------------------------------------ #
# unit: client-side filter verdict (freshness bound)
# ------------------------------------------------------------------ #

def test_filter_verdict_tristate_and_staleness_bound():
    c = SmartClient(cfg=ClientConfig(filter_max_age_s=1.0))
    d_in = sha256_hex(b"present")
    d_out = sha256_hex(b"absent")
    bloom = BlockedBloomFilter(64, 10)
    bloom.add(d_in)
    now = time.monotonic()
    c._filters = {3: {"bloom": bloom, "gen": 1,
                      "fetchedAt": now, "baseAgeS": 0.0}}
    assert c._filter_verdict(3, d_in) is True      # maybe: verify
    assert c._filter_verdict(3, d_out) is False    # definitely absent
    assert c._filter_verdict(9, d_in) is None      # no filter: probe
    # past the freshness bound (server-side age counts too): unusable
    c._filters[3]["baseAgeS"] = 5.0
    assert c._filter_verdict(3, d_in) is None
    assert c._filter_verdict(3, d_out) is None


# ------------------------------------------------------------------ #
# in-process cluster: smart path end to end
# ------------------------------------------------------------------ #

def test_smart_upload_download_byte_identity(tmp_path):
    """Fresh upload stripes rf copies directly to the owners, commits
    in one call, and the striped download re-verifies every chunk —
    byte-identical from every node, including via the legacy path."""

    async def run() -> None:
        cluster = _mk_cluster(3, rf=2)
        nodes = await _start_nodes(cluster, tmp_path, index=IX)
        try:
            c = _smart(cluster)
            data = os.urandom(250_000)
            info = await asyncio.to_thread(c.upload, data, "a.bin")
            assert info["dataPlane"] == "smart"
            assert info["fileId"] == sha256_hex(data)
            # rf copies crossed the wire (fresh corpus, no dedup)
            assert c.counters["transferredBytes"] == 2 * len(data)
            got = await asyncio.to_thread(c.download, info["fileId"])
            assert got == data
            assert c.counters["smartDownloads"] == 1
            assert c.counters["chunksVerified"] >= info["chunks"]
            # interop: the acked file reads back through EVERY node's
            # legacy coordinator path byte-identically
            for n in nodes.values():
                _, body = await n.download(info["fileId"])
                assert bytes(body) == data
            st = c.stats()
            assert st["smart"] and st["fallbacks"] == 0
            assert st["window"] == 2 and st["fallback"] is False
        finally:
            await _stop_all(nodes)

    asyncio.run(run())


def test_smart_reupload_dedups_via_filters(tmp_path):
    """Once filters have gossiped, a second client re-uploading the
    same corpus transfers ZERO payload bytes: filter credits are
    trust-verified pre-commit, never taken on faith."""

    async def run() -> None:
        cluster = _mk_cluster(3, rf=2)
        nodes = await _start_nodes(cluster, tmp_path, index=IX)
        try:
            data = os.urandom(250_000)
            c1 = _smart(cluster)
            info = await asyncio.to_thread(c1.upload, data, "a.bin")
            assert info["dataPlane"] == "smart"
            for n in nodes.values():
                await n._filter_sync_once()
            c2 = _smart(cluster, nid=2)
            info2 = await asyncio.to_thread(c2.upload, data, "a.bin")
            assert info2["fileId"] == info["fileId"]
            assert c2.counters["transferredBytes"] == 0
            assert c2.counters["dedupSkippedBytes"] == 2 * len(data)
            assert c2.counters["verifyRpcs"] >= 1   # the trust round
            assert c2.counters["filterFp"] == 0
        finally:
            await _stop_all(nodes)

    asyncio.run(run())


def test_stale_corrupt_filter_degrades_to_probes_never_loses_bytes(
        tmp_path):
    """Satellite: a deliberately corrupt filter replica (every bit
    set — it claims EVERYTHING exists) must cost extra RPCs and real
    sends, never an acked manifest naming bytes that do not exist.
    A stale replica (past the freshness bound) must degrade to plain
    probes. Both uploads ack and read back byte-identical."""

    async def run() -> None:
        cluster = _mk_cluster(3, rf=2)
        nodes = await _start_nodes(cluster, tmp_path, index=IX)
        try:
            c = _smart(cluster)
            # seed: a first upload fetches the filter replicas
            await asyncio.to_thread(c.upload, os.urandom(50_000), "s")
            assert c._filters is not None
            # corrupt every fetched replica: all-ones bloom = "present"
            # for every digest ever asked
            for st in c._filters.values():
                buf = st["bloom"].buf
                for i in range(len(buf)):
                    buf[i] = 0xFF
            fresh = os.urandom(200_000)
            info = await asyncio.to_thread(c.upload, fresh, "fresh.bin")
            assert info["dataPlane"] == "smart"
            # the lie was caught first-party: verification probes ran,
            # false positives were counted, and REAL bytes were sent
            assert c.counters["verifyRpcs"] >= 1
            assert c.counters["filterFp"] > 0
            assert c.counters["transferredBytes"] >= len(fresh)
            for n in nodes.values():
                _, body = await n.download(info["fileId"])
                assert bytes(body) == fresh
            got = await asyncio.to_thread(c.download, info["fileId"])
            assert got == fresh

            # stale replica: age past the bound -> verdict None ->
            # plain probe RPCs (extra round trips, correct bytes)
            for st in c._filters.values():
                st["baseAgeS"] = 10_000.0
            probes_before = c.counters["probeRpcs"]
            fresh2 = os.urandom(120_000)
            info2 = await asyncio.to_thread(c.upload, fresh2, "f2.bin")
            assert c.counters["probeRpcs"] > probes_before
            got2 = await asyncio.to_thread(c.download, info2["fileId"])
            assert got2 == fresh2
        finally:
            await _stop_all(nodes)

    asyncio.run(run())


def test_echo_cache_skips_verify_round_on_reupload(tmp_path):
    """Satellite: a digest whose hash-echo was confirmed THIS session
    skips even the trust-verification round on re-upload; a ring epoch
    change clears every session confirmation."""

    async def run() -> None:
        cluster = _mk_cluster(3, rf=2)
        nodes = await _start_nodes(cluster, tmp_path, index=IX)
        try:
            c = _smart(cluster, echo_cache_entries=4096)
            data = os.urandom(150_000)
            info = await asyncio.to_thread(c.upload, data, "a.bin")
            v_before = c.counters["verifyRpcs"]
            p_before = c.counters["probeRpcs"]
            info2 = await asyncio.to_thread(c.upload, data, "b.bin")
            assert info2["fileId"] == info["fileId"]
            # every owner copy was echo-confirmed at store time: the
            # re-upload needs NO probe and NO verify round
            assert c.counters["verifyRpcs"] == v_before
            assert c.counters["probeRpcs"] == p_before
            assert c.counters["transferredBytes"] == 2 * len(data)
            assert c.counters["dedupSkippedBytes"] >= 2 * len(data)
            # epoch change invalidates the session cache
            c._echo.note_epoch(c._ringview.epoch + 1)
            assert c._echo.stats()["entries"] == 0
        finally:
            await _stop_all(nodes)

    asyncio.run(run())


# ------------------------------------------------------------------ #
# in-process cluster: fallback matrix
# ------------------------------------------------------------------ #

def test_old_server_pins_client_to_legacy_path(tmp_path):
    """A server without /dataplane (pre-r19) 404s the bootstrap: the
    client pins itself to the legacy coordinator path for life and
    stays byte-identical."""

    async def run() -> None:
        cluster = _mk_cluster(2, rf=2)
        nodes = await _start_nodes(cluster, tmp_path)
        try:
            c = SmartClient(host="127.0.0.1", port=cluster.peer(1).port,
                            cfg=ClientConfig())
            orig = c.legacy._request

            def no_dataplane(method, path, *a, **kw):
                if path == "/dataplane":
                    raise RuntimeError("HTTP 404: Not Found")
                return orig(method, path, *a, **kw)

            c.legacy._request = no_dataplane
            data = os.urandom(100_000)
            info = await asyncio.to_thread(c.upload, data, "a.bin")
            assert info["dataPlane"] == "legacy"
            assert info["fileId"] == sha256_hex(data)
            got = await asyncio.to_thread(c.download, info["fileId"])
            assert got == data
            assert c.counters["legacyUploads"] == 1
            assert c.counters["legacyDownloads"] == 1
            assert c._boot is False      # pinned: no re-probe
        finally:
            await _stop_all(nodes)

    asyncio.run(run())


def test_no_fallback_raises_instead_of_degrading(tmp_path):
    async def run() -> None:
        cluster = _mk_cluster(1, rf=1)
        nodes = await _start_nodes(cluster, tmp_path)
        try:
            c = _smart(cluster)          # fallback=False
            c.legacy._request = _raise_404
            with pytest.raises(SmartClientError):
                await asyncio.to_thread(c.upload, b"x" * 10_000, "a")
        finally:
            await _stop_all(nodes)

    asyncio.run(run())


def _raise_404(method, path, *a, **kw):
    raise RuntimeError("HTTP 404: Not Found")


def test_ec_manifest_downloads_via_legacy_path(tmp_path):
    """EC stripes are a coordinator-side reconstruction concern: the
    smart client detects the manifest and hands the read to the legacy
    path (byte-identical), counting the fallback."""

    async def run() -> None:
        cluster = _mk_cluster(4, rf=2)
        nodes = await _start_nodes(cluster, tmp_path)
        try:
            data = os.urandom(120_000)
            m, _ = await nodes[1].upload(data, "e.bin", ec_k=2)
            c = SmartClient(host="127.0.0.1", port=cluster.peer(1).port,
                            cfg=ClientConfig())
            got = await asyncio.to_thread(c.download, m.file_id)
            assert got == data
            assert c.counters["legacyDownloads"] == 1
            assert c.counters["fallbacks"] == 1
        finally:
            await _stop_all(nodes)

    asyncio.run(run())


# ------------------------------------------------------------------ #
# in-process cluster: the /commit quorum re-count
# ------------------------------------------------------------------ #

def test_commit_refuses_phantom_chunks_with_409(tmp_path):
    """A manifest naming chunks held NOWHERE must never ack: the
    coordinator's own has_chunks re-count raises the 409-class error
    and no manifest is saved (a stale client filter cannot manufacture
    durability)."""

    async def run() -> None:
        cluster = _mk_cluster(2, rf=2)
        nodes = await _start_nodes(cluster, tmp_path)
        try:
            body = os.urandom(30_000)
            dg = sha256_hex(body)
            fid = sha256_hex(b"claimed-stream")
            with pytest.raises(UploadError) as ei:
                await nodes[1].commit_manifest(
                    [(0, len(body), dg)], "ghost.bin", fid, len(body))
            assert ei.value.status == 409
            with pytest.raises(KeyError):
                await nodes[1].download(fid)
        finally:
            await _stop_all(nodes)

    asyncio.run(run())


def test_commit_heals_below_quorum_chunk_before_ack(tmp_path):
    """A chunk present on ONE owner but below write quorum is healed
    through the normal placement path before the ack — commit needs
    real durability, not one lucky copy."""

    async def run() -> None:
        cluster = _mk_cluster(2, rf=2)
        nodes = await _start_nodes(cluster, tmp_path)
        try:
            body = os.urandom(40_000)
            dg = sha256_hex(body)
            # stage on node 1 ONLY (one copy; quorum is 2)
            assert await nodes[1].cas.put(dg, body)
            fid = sha256_hex(body)       # single-chunk stream
            manifest, stats = await nodes[1].commit_manifest(
                [(0, len(body), dg)], "heal.bin", fid, len(body))
            assert stats["minCopies"] >= 2
            # the heal landed a REAL copy on the peer
            _, got = await nodes[2].download(fid)
            assert bytes(got) == body
        finally:
            await _stop_all(nodes)

    asyncio.run(run())


def test_commit_of_fully_present_chunks_is_pure_dedup(tmp_path):
    async def run() -> None:
        cluster = _mk_cluster(2, rf=2)
        nodes = await _start_nodes(cluster, tmp_path)
        try:
            data = os.urandom(80_000)
            m, _ = await nodes[1].upload(data, "orig.bin")
            table = [(c.offset, c.length, c.digest) for c in m.chunks]
            m2, stats = await nodes[1].commit_manifest(
                table, "alias.bin", m.file_id, len(data))
            assert stats["transferredBytes"] == 0
            assert stats["dedupSkippedBytes"] == len(data)
            assert stats["minCopies"] >= 2
        finally:
            await _stop_all(nodes)

    asyncio.run(run())


# ------------------------------------------------------------------ #
# hedged writes (r18 leftover): pulsing-slow replica
# ------------------------------------------------------------------ #

def test_hedged_write_beats_pulsing_slow_replica(tmp_path):
    """Satellite: with a pulsing-slow replica (chaos serve delay
    toggled on/off across uploads), the coordinator hedges the
    store_chunks slice train to the next holder under the existing
    token budget — hedge_fired/hedge_won journal evidence with
    op=store_chunks — and every acked byte reads back from every
    node."""

    async def run() -> None:
        cluster = _mk_cluster(3, rf=2)
        hedged = ServeConfig(hedge_budget_per_s=50.0,
                             hedge_floor_s=0.05, hedge_cap_s=0.3)
        nodes = await _start_nodes(
            cluster, tmp_path,
            overrides={1: {"serve": hedged},
                       3: {"chaos": ChaosConfig(enabled=True)}})
        try:
            uploaded: list[tuple[str, bytes]] = []
            fired_total = 0
            for pulse in range(2):
                nodes[3].chaos.set(serve_delay_s=0.25)
                # ~25 chunks: ~1/3 land in a {1,3} owner set where the
                # remote train targets slow node 3 with node 2 free as
                # the hedge backup
                data = os.urandom(200_000)
                m, _ = await nodes[1].upload(data, f"p{pulse}.bin")
                uploaded.append((m.file_id, data))
                nodes[3].chaos.set(serve_delay_s=0.0)   # pulse ends
                calm = os.urandom(60_000)
                mc, _ = await nodes[1].upload(calm, f"c{pulse}.bin")
                uploaded.append((mc.file_id, calm))
            hs = nodes[1].serve.hedge.stats()
            assert hs["fired"] >= 1 and hs["won"] >= 1
            await asyncio.to_thread(nodes[1].obs.journal.flush)
            tail = await asyncio.to_thread(nodes[1].obs.journal.tail,
                                           0.0, 1024)
            store_hedges = [e for e in tail["events"]
                            if e.get("type") in ("hedge_fired",
                                                 "hedge_won")
                            and e.get("op") == "store_chunks"]
            assert store_hedges, "no store-side hedge evidence"
            # zero acked-byte loss through the pulses — from EVERY node
            for fid, want in uploaded:
                for n in nodes.values():
                    _, body = await n.download(fid)
                    assert bytes(body) == want
        finally:
            await _stop_all(nodes)

    asyncio.run(run())


# ------------------------------------------------------------------ #
# background index compaction (r16 leftover)
# ------------------------------------------------------------------ #

def test_background_compaction_off_worker_thread(tmp_path):
    """Satellite: with background_compact=True the full compaction
    runs on the dedicated thread — note() returns without folding runs
    inline, drain_compaction() reaches the folded state, and the
    stall-attribution counters surface."""
    idx = DigestIndex(tmp_path / "ix", memtable_entries=256,
                      compact_runs=2, background_compact=True)
    assert idx.open_or_rebuild(lambda: [])["rebuilt"] is False
    try:
        for batch in range(6):
            for i in range(256):
                idx.note_put(sha256_hex(f"{batch}:{i}".encode()))
        idx.drain_compaction()
        st = idx.stats()
        assert st["compactions"] >= 1
        assert st["runCount"] <= 3       # folded to (about) one base
        assert st["bgCompactS"] > 0.0    # the thread did the folding
        assert st["compactStallS"] == 0.0  # CAS workers never stalled
        # every key still resolves after the background fold
        assert idx.lookup(sha256_hex(b"0:0"))
        assert idx.lookup(sha256_hex(b"5:255"))
    finally:
        idx.close()


def test_inline_mode_unchanged_and_drain_is_noop(tmp_path):
    idx = DigestIndex(tmp_path / "ix", memtable_entries=256,
                      compact_runs=2)
    assert idx.open_or_rebuild(lambda: [])["rebuilt"] is False
    try:
        for batch in range(6):
            for i in range(256):
                idx.note_put(sha256_hex(f"{batch}:{i}".encode()))
        idx.drain_compaction()           # inline mode: returns at once
        st = idx.stats()
        assert st["compactions"] >= 1    # folded inline, as before
        assert st["bgCompactS"] == 0.0   # no thread involved
        assert idx.lookup(sha256_hex(b"3:7"))
    finally:
        idx.close()


# ------------------------------------------------------------------ #
# bench smoke (tier-1)
# ------------------------------------------------------------------ #

def test_bench_client_tiny_smoke(tmp_path):
    """bench_client.py --tiny end to end as a subprocess: every gate
    runs against a real multi-process cluster and the artifact schema
    locks (CLIENT_r19.json shape)."""
    out = tmp_path / "client.json"
    r = subprocess.run(
        [sys.executable, str(REPO / "bench_client.py"), "--tiny",
         "--out", str(out)],
        cwd=tmp_path, env={**os.environ, "JAX_PLATFORMS": "cpu",
                           "PYTHONPATH": str(REPO)},
        capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    rep = json.loads(out.read_text())
    assert rep["metric"] == "client_data_plane"
    assert rep["tiny"] is True and rep["ok"] is True
    for gate in ("dedup_reupload", "striped_speedup",
                 "verified_stale_and_slow", "interop"):
        assert gate in rep["gates"], rep["gates"]
        assert rep["gates"][gate]["ok"] is True, rep["gates"][gate]
