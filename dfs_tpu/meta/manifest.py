"""Manifest v2 — chunk-granular file metadata.

The reference manifest is ``{fileId, originalName, totalFragments}`` built by
string concatenation (StorageNode.java:620-626) and parsed with ``indexOf``
hacks (StorageNode.java:657-773). Two deliberate upgrades (SURVEY.md §2.5(7)):

1. per-chunk SHA-256 digests + (offset, length) are stored in the manifest, so
   download can verify every chunk independently and the dedup index can
   address chunks by content — the reference computes fragment hashes
   (StorageNode.java:159) but throws them away;
2. serialization is real JSON (stdlib), not a hand-rolled codec that breaks on
   escaped quotes (reference defect, SURVEY.md S14).
"""

from __future__ import annotations

import dataclasses
import json


@dataclasses.dataclass(frozen=True)
class ChunkRef:
    """One content-addressed chunk of a file."""

    index: int
    offset: int
    length: int
    digest: str  # lowercase-hex sha256 of the chunk bytes


@dataclasses.dataclass(frozen=True)
class Manifest:
    """Whole-file metadata. ``file_id`` remains sha256(file bytes) exactly as
    in the reference (StorageNode.java:127), preserving whole-file dedup."""

    file_id: str
    name: str
    size: int
    fragmenter: str               # "fixed" | "cdc" | "cdc-tpu"
    chunks: tuple[ChunkRef, ...]

    def __post_init__(self) -> None:
        covered = 0
        for i, c in enumerate(self.chunks):
            if c.index != i:
                raise ValueError(f"chunk index mismatch at {i}")
            if c.offset != covered:
                raise ValueError(f"chunk offset gap at {i}")
            covered += c.length
        if covered != self.size:
            raise ValueError(f"chunks cover {covered} bytes, size is {self.size}")

    @property
    def total_chunks(self) -> int:
        return len(self.chunks)

    def digests(self) -> list[str]:
        return [c.digest for c in self.chunks]

    def to_json(self) -> str:
        return json.dumps({
            "version": 2,
            "fileId": self.file_id,
            "originalName": self.name,
            "size": self.size,
            "fragmenter": self.fragmenter,
            "totalFragments": len(self.chunks),  # reference-compat field name
            "chunks": [dataclasses.asdict(c) for c in self.chunks],
        }, indent=None, separators=(",", ":"))

    @staticmethod
    def from_json(text: str | bytes) -> "Manifest":
        d = json.loads(text)
        return Manifest(
            file_id=d["fileId"],
            name=d.get("originalName", d["fileId"]),
            size=d["size"],
            fragmenter=d.get("fragmenter", "fixed"),
            chunks=tuple(ChunkRef(**c) for c in d["chunks"]),
        )
