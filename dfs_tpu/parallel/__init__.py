from dfs_tpu.parallel.mesh import make_mesh  # noqa: F401
from dfs_tpu.parallel.sharded_cdc import make_sharded_step  # noqa: F401
