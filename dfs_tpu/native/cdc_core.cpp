// Native CPU core: SHA-256 + Gear rolling-hash CDC.
//
// Role (SURVEY.md §2, "native equivalents"): the reference is pure Java with
// zero native code; in this framework the TPU owns the hot path
// (dfs_tpu/ops), and this C++ library is the node runtime's *host* engine —
// used when no accelerator is attached (pure-CPU storage nodes), for the
// hash-echo recomputation on the receive path, and as a fast oracle for
// tests/benchmarks. Exposed to Python via ctypes (no pybind11 in the image).
//
// Build: dfs_tpu/native/build.py  (g++ -O3 -shared -fPIC)

#include <cstdint>
#include <cstring>
#include <cstddef>
#include <new>

namespace {

constexpr uint32_t K[64] = {
    0x428a2f98u, 0x71374491u, 0xb5c0fbcfu, 0xe9b5dba5u, 0x3956c25bu,
    0x59f111f1u, 0x923f82a4u, 0xab1c5ed5u, 0xd807aa98u, 0x12835b01u,
    0x243185beu, 0x550c7dc3u, 0x72be5d74u, 0x80deb1feu, 0x9bdc06a7u,
    0xc19bf174u, 0xe49b69c1u, 0xefbe4786u, 0x0fc19dc6u, 0x240ca1ccu,
    0x2de92c6fu, 0x4a7484aau, 0x5cb0a9dcu, 0x76f988dau, 0x983e5152u,
    0xa831c66du, 0xb00327c8u, 0xbf597fc7u, 0xc6e00bf3u, 0xd5a79147u,
    0x06ca6351u, 0x14292967u, 0x27b70a85u, 0x2e1b2138u, 0x4d2c6dfcu,
    0x53380d13u, 0x650a7354u, 0x766a0abbu, 0x81c2c92eu, 0x92722c85u,
    0xa2bfe8a1u, 0xa81a664bu, 0xc24b8b70u, 0xc76c51a3u, 0xd192e819u,
    0xd6990624u, 0xf40e3585u, 0x106aa070u, 0x19a4c116u, 0x1e376c08u,
    0x2748774cu, 0x34b0bcb5u, 0x391c0cb3u, 0x4ed8aa4au, 0x5b9cca4fu,
    0x682e6ff3u, 0x748f82eeu, 0x78a5636fu, 0x84c87814u, 0x8cc70208u,
    0x90befffau, 0xa4506cebu, 0xbef9a3f7u, 0xc67178f2u};

inline uint32_t rotr(uint32_t x, int n) {
  return (x >> n) | (x << (32 - n));
}

// lowbias32 finalizer — must match dfs_tpu/ops/cdc_anchored._fmix32_np /
// cdc_v2.fmix32_np exactly.
inline uint32_t fmix32(uint32_t x) {
  x ^= x >> 16;
  x *= 0x7FEB352Du;
  x ^= x >> 15;
  x *= 0x846CA68Bu;
  return x ^ (x >> 16);
}

void compress(uint32_t state[8], const uint8_t* block) {
  uint32_t w[64];
  for (int t = 0; t < 16; ++t) {
    w[t] = (uint32_t(block[4 * t]) << 24) | (uint32_t(block[4 * t + 1]) << 16) |
           (uint32_t(block[4 * t + 2]) << 8) | uint32_t(block[4 * t + 3]);
  }
  for (int t = 16; t < 64; ++t) {
    uint32_t s0 = rotr(w[t - 15], 7) ^ rotr(w[t - 15], 18) ^ (w[t - 15] >> 3);
    uint32_t s1 = rotr(w[t - 2], 17) ^ rotr(w[t - 2], 19) ^ (w[t - 2] >> 10);
    w[t] = w[t - 16] + s0 + w[t - 7] + s1;
  }
  uint32_t a = state[0], b = state[1], c = state[2], d = state[3];
  uint32_t e = state[4], f = state[5], g = state[6], h = state[7];
  for (int t = 0; t < 64; ++t) {
    uint32_t s1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = h + s1 + ch + K[t] + w[t];
    uint32_t s0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = s0 + maj;
    h = g; g = f; f = e; e = d + t1; d = c; c = b; b = a; a = t1 + t2;
  }
  state[0] += a; state[1] += b; state[2] += c; state[3] += d;
  state[4] += e; state[5] += f; state[6] += g; state[7] += h;
}

}  // namespace

extern "C" {

// SHA-256 of one message; out = 32 raw bytes.
void dfs_sha256(const uint8_t* data, uint64_t len, uint8_t* out) {
  uint32_t st[8] = {0x6a09e667u, 0xbb67ae85u, 0x3c6ef372u, 0xa54ff53au,
                    0x510e527fu, 0x9b05688cu, 0x1f83d9abu, 0x5be0cd19u};
  uint64_t full = len / 64;
  for (uint64_t i = 0; i < full; ++i) compress(st, data + 64 * i);
  uint8_t tail[128];
  uint64_t rem = len - 64 * full;
  std::memset(tail, 0, sizeof(tail));
  std::memcpy(tail, data + 64 * full, rem);
  tail[rem] = 0x80;
  uint64_t tail_blocks = (rem + 9 <= 64) ? 1 : 2;
  uint64_t bits = len * 8;
  for (int i = 0; i < 8; ++i)
    tail[tail_blocks * 64 - 1 - i] = uint8_t(bits >> (8 * i));
  compress(st, tail);
  if (tail_blocks == 2) compress(st, tail + 64);
  for (int i = 0; i < 8; ++i) {
    out[4 * i] = uint8_t(st[i] >> 24);
    out[4 * i + 1] = uint8_t(st[i] >> 16);
    out[4 * i + 2] = uint8_t(st[i] >> 8);
    out[4 * i + 3] = uint8_t(st[i]);
  }
}

// Batch: messages concatenated in `data`, offsets[i]..offsets[i+1] per
// message (offsets has n+1 entries); out = n * 32 bytes.
void dfs_sha256_batch(const uint8_t* data, const uint64_t* offsets,
                      uint64_t n, uint8_t* out) {
  for (uint64_t i = 0; i < n; ++i)
    dfs_sha256(data + offsets[i], offsets[i + 1] - offsets[i], out + 32 * i);
}

// Sequential Gear CDC cut selection (the same algorithm as
// dfs_tpu/ops/boundary.py): writes exclusive cut offsets into `cuts`
// (capacity cuts_cap), returns the number written, or -1 on overflow.
// table: 256 uint32 Gear entries; boundary iff (h & mask)==0 at
// length>=min_size; forced cut at max_size.
int64_t dfs_gear_cuts(const uint8_t* data, uint64_t len,
                      const uint32_t* table, uint32_t mask,
                      uint64_t min_size, uint64_t max_size,
                      uint64_t* cuts, uint64_t cuts_cap) {
  uint32_t h = 0;
  uint64_t start = 0, n_cuts = 0;
  for (uint64_t i = 0; i < len; ++i) {
    h = (h << 1) + table[data[i]];
    uint64_t chunk_len = i - start + 1;
    bool cut = (chunk_len >= min_size && (h & mask) == 0) ||
               chunk_len >= max_size;
    if (cut) {
      if (n_cuts == cuts_cap) return -1;
      cuts[n_cuts++] = i + 1;
      start = i + 1;
    }
  }
  if (start < len) {
    if (n_cuts == cuts_cap) return -1;
    cuts[n_cuts++] = len;
  }
  return int64_t(n_cuts);
}

// Anchored two-level CDC spans for ONE WINDOW of a longer stream —
// region edition of dfs_anchored_spans, mirroring the device walk's
// contract (dfs_tpu/ops/cdc_anchored.region_chunks): `lookback` holds
// the 8 stream bytes before data[0] (zeros at true stream start; the
// window base must be tile-aligned in the stream so first-per-tile
// anchor quantization matches the whole-stream result); `start0` is the
// carry position inside the window (bytes before it belong to segments
// a previous window already emitted); `final` != 0 iff the stream ends
// at data[len-1] — otherwise the unfinished tail segment is withheld so
// its bytes carry into the next window. Writes region-local (offset,
// length) pairs; sets *consumed to the bound segments were emitted up
// to (== len when final). Returns the pair count, or -1 on
// overflow/alloc failure.
int64_t dfs_anchored_spans_region(const uint8_t* data, uint64_t len,
                                  const uint8_t* lookback, uint64_t start0,
                                  int final_region, uint32_t anchor_seed,
                                  uint32_t seg_mask, uint64_t seg_min,
                                  uint64_t seg_max, uint64_t tile_bytes,
                                  uint32_t chunk_seed, uint32_t avg_mask,
                                  uint64_t min_blocks, uint64_t max_blocks,
                                  uint64_t* spans, uint64_t span_cap,
                                  uint64_t* consumed) {
  *consumed = start0;
  if (len == 0) return 0;

  // ---- pass A: first TWO qualifying anchors per tile (-1 = none),
  // interleaved [first, second] per tile — mirrors the device pass-A
  // two-plane output (dfs_tpu/ops/cdc_anchored.make_anchor_fn) ----
  uint64_t n_tiles = (len + tile_bytes - 1) / tile_bytes;
  int64_t* tile_anchor = new (std::nothrow) int64_t[2 * n_tiles];
  if (!tile_anchor) return -1;
  for (uint64_t t = 0; t < 2 * n_tiles; ++t) tile_anchor[t] = -1;
  uint64_t reg = 0;  // bytes[p-7..p], data[p] in the top byte (LE window)
  for (int i = 0; i < 8; ++i)
    reg = (reg >> 8) | (uint64_t(lookback[i]) << 56);
  for (uint64_t p = 0; p < len; ++p) {
    reg = (reg >> 8) | (uint64_t(data[p]) << 56);
    uint32_t b = uint32_t(reg >> 32);
    uint32_t a = uint32_t(reg);
    uint32_t h = fmix32(fmix32(b) + anchor_seed + a);
    if ((h & seg_mask) == 0) {
      uint64_t t = p / tile_bytes;
      if (tile_anchor[2 * t] < 0) tile_anchor[2 * t] = int64_t(p);
      else if (tile_anchor[2 * t + 1] < 0) tile_anchor[2 * t + 1] = int64_t(p);
    }
  }

  // ---- G table for the aligned windowed Gear (arithmetic form) ----
  uint32_t G[256];
  for (uint32_t v = 0; v < 256; ++v)
    G[v] = fmix32(chunk_seed ^ (v * 0x9E3779B1u));

  // ---- segment walk + per-segment aligned chunking ----
  uint64_t n_spans = 0, start = start0;
  bool ok = true;
  while (ok) {
    uint64_t bound;
    if (len - start <= seg_max) {
      if (!final_region) break;  // tail carries into the next window
      bound = len;               // final segment
    } else {
      // last kept anchor a with start+seg_min <= a+1 <= start+seg_max;
      // within a tile the second kept anchor is the larger, so it is
      // checked first
      uint64_t lo = start + seg_min - 1, hi = start + seg_max - 1;
      int64_t found = -1;
      for (uint64_t t = hi / tile_bytes + 1; t-- > lo / tile_bytes;) {
        for (int j = 1; j >= 0 && found < 0; --j) {
          int64_t a = tile_anchor[2 * t + j];
          if (a >= int64_t(lo) && a <= int64_t(hi)) found = a;
        }
        if (found >= 0) break;
      }
      bound = found >= 0 ? uint64_t(found) + 1 : start + seg_max;
    }

    // aligned chunking of segment [start, bound), grid re-anchored
    uint64_t seg_len = bound - start;
    uint64_t nb = (seg_len + 63) / 64;         // incl. trailing partial
    uint64_t full = seg_len / 64;              // candidate-eligible blocks
    uint64_t since = 0, prev = 0;
    for (uint64_t t = 0; t < nb; ++t) {
      ++since;
      bool cand = false;
      if (t < full) {
        const uint8_t* blk = data + start + 64 * t;
        uint32_t h = 0;
        for (int k = 0; k < 32; ++k) h += G[blk[63 - k]] << k;
        cand = (h & avg_mask) == 0;
      }
      bool cut = (cand && since >= min_blocks) || since >= max_blocks ||
                 t == nb - 1;
      if (cut) {
        if (n_spans == span_cap) { ok = false; break; }
        uint64_t end = (t + 1) * 64 < seg_len ? (t + 1) * 64 : seg_len;
        spans[2 * n_spans] = start + prev * 64;
        spans[2 * n_spans + 1] = end - prev * 64;
        ++n_spans;
        prev = t + 1;
        since = 0;
      }
    }
    if (!ok) break;
    start = bound;
    if (bound == len) break;
  }
  delete[] tile_anchor;
  *consumed = start;
  return ok ? int64_t(n_spans) : -1;
}

// Whole-stream spans — bit-identical to the NumPy oracle
// (dfs_tpu/ops/cdc_anchored.chunk_spans_anchored_np). One final region
// starting from a zero lookback.
int64_t dfs_anchored_spans(const uint8_t* data, uint64_t len,
                           uint32_t anchor_seed, uint32_t seg_mask,
                           uint64_t seg_min, uint64_t seg_max,
                           uint64_t tile_bytes, uint32_t chunk_seed,
                           uint32_t avg_mask, uint64_t min_blocks,
                           uint64_t max_blocks, uint64_t* spans,
                           uint64_t span_cap) {
  uint8_t zeros[8] = {0, 0, 0, 0, 0, 0, 0, 0};
  uint64_t consumed = 0;
  return dfs_anchored_spans_region(
      data, len, zeros, 0, 1, anchor_seed, seg_mask, seg_min, seg_max,
      tile_bytes, chunk_seed, avg_mask, min_blocks, max_blocks, spans,
      span_cap, &consumed);
}

}  // extern "C"
