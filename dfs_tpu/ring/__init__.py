"""Elastic membership plane: the weighted consistent-hash ring
(docs/membership.md).

The cluster placed chunks with a fixed, boot-time peer list and cyclic
mod-N replica sets until r14 — adding or removing ONE node silently
reassigned ~all digests (the mod changes), so the membership could never
change live. This package is the Dynamo/CRUSH-shaped fix:

- :class:`RingMap` — a compact, deterministic description of WHO owns
  WHAT: ``{epoch, vnodes, members:[{node_id, weight, vnodes_seed}]}``.
  Any party holding the map computes, for any digest, the exact owner
  list (``owners``) — no directory service, no per-digest state. Two
  modes share the class:

  * **static** (``vnodes == 0``) — the legacy epoch-0 placement: cyclic
    replica sets over the sorted member ids (``int(digest[:16], 16)
    mod N``). BYTE-STABLE with the pre-r14 ``node.placement`` math by
    construction (the functions moved here; placement.py is now a shim)
    so existing stores keep their layout when no ring flag is set.
  * **hash** (``vnodes > 0``) — the weighted consistent-hash ring:
    each member projects ``round(weight * vnodes)`` virtual nodes onto
    a 64-bit circle (positions are sha256 of ``"<node_id>:<seed>:<i>"``
    — deterministic from the map alone); a digest's owners are the
    first ``rf`` DISTINCT members clockwise from its point. Adding one
    member at equal weight moves ~1/(N+1) of the digest space and
    nothing else (tests/test_ring.py pins both the balance and the
    minimal-movement property); weight 0 (drain) owns nothing.

- epoch versioning — every membership change is a NEW map with
  ``epoch + 1``. Maps are propagated via the ``propose_ring`` /
  ``get_ring`` internal ops and every placement-bearing RPC carries its
  sender's epoch, so a stale node answers ``ring epoch mismatch``
  (and the two sides converge on the higher epoch) instead of silently
  mis-placing (comm/rpc.py, node/runtime.py).

- :class:`dfs_tpu.ring.manager.RingManager` — one node's live ring
  state: current + previous map (the dual-read migration window), the
  byte-credit bucket bounding rebalance bandwidth, and the migration
  progress counters ``/metrics`` serves.
"""

from __future__ import annotations

import bisect
import dataclasses
from typing import Sequence

from dfs_tpu.utils.hashing import sha256_hex

# leading hex chars of a digest used as its 64-bit ring point — the
# same prefix the legacy static placement hashed, so the two modes
# read one digest the same way
POINT_HEX = 16
# vnodes per unit weight a membership CHANGE promotes a static ring to
# (a static map cannot express minimal movement; the first live
# add/remove/drain switches the cluster to consistent hashing)
DEFAULT_VNODES = 64


def _point(key: str) -> int:
    """64-bit ring position of an arbitrary string key."""
    return int(sha256_hex(key.encode())[:POINT_HEX], 16)


def digest_point(digest: str) -> int:
    """64-bit ring position of a (hex) content digest — its leading 64
    bits, exactly what the static mod-N placement hashed."""
    return int(digest[:POINT_HEX], 16)


# ------------------------------------------------------------------ #
# the legacy static placement math (moved verbatim from node/placement;
# node.placement's public functions are now thin shims over these via
# RingMap.static — the byte-stability contract of epoch-0 clusters)
# ------------------------------------------------------------------ #

def static_replica_set(digest: str, node_ids: list[int],
                       rf: int) -> list[int]:
    if not node_ids:
        raise ValueError("empty cluster")
    rf = min(rf, len(node_ids))
    start = digest_point(digest) % len(node_ids)
    return [node_ids[(start + j) % len(node_ids)] for j in range(rf)]


def static_ec_shard_node(file_id: str, stripe: int, shard: int,
                         node_ids: list[int]) -> int:
    if not node_ids:
        raise ValueError("empty cluster")
    base = (int(file_id[:16], 16) + stripe * 2654435761) % len(node_ids)
    return node_ids[(base + shard) % len(node_ids)]


def static_handoff_order(pinned: Sequence[int],
                         node_ids: list[int]) -> list[int]:
    if not pinned:
        return list(node_ids)
    start = node_ids.index(pinned[0]) if pinned[0] in node_ids else 0
    ring = [node_ids[(start + j) % len(node_ids)]
            for j in range(len(node_ids))]
    return list(dict.fromkeys(list(pinned) + ring))


@dataclasses.dataclass(frozen=True)
class RingMember:
    """One member of the placement ring. ``weight`` scales the share of
    the digest space the member owns (0 = draining: a member that owns
    nothing but is still listed — ``ring status`` shows it on its way
    out); ``vnodes_seed`` salts its vnode positions so a re-added
    member can be given fresh positions if its old arc is pathological
    (never needed in practice; kept 0)."""

    node_id: int
    weight: float = 1.0
    vnodes_seed: int = 0

    def __post_init__(self) -> None:
        if self.node_id < 0:
            raise ValueError("node_id must be >= 0")
        if not (0.0 <= float(self.weight) <= 1024.0):
            raise ValueError("weight must be in [0, 1024]")


@dataclasses.dataclass(frozen=True)
class RingMap:
    """A compact, deterministic placement map (module docstring). The
    vnode table is built lazily once per instance and cached — maps are
    immutable, epoch-versioned values."""

    epoch: int
    members: tuple[RingMember, ...]
    vnodes: int = 0            # 0 = static (legacy) mode

    def __post_init__(self) -> None:
        if self.epoch < 0:
            raise ValueError("epoch must be >= 0")
        if self.vnodes < 0:
            raise ValueError("vnodes must be >= 0")
        ids = [m.node_id for m in self.members]
        if len(set(ids)) != len(ids):
            raise ValueError("duplicate node_id in ring members")
        if self.vnodes == 0 and any(m.weight not in (1, 1.0)
                                    for m in self.members):
            raise ValueError("static mode (vnodes=0) cannot express "
                             "weights — set vnodes > 0")

    # ---- construction ------------------------------------------------ #

    @staticmethod
    def static(node_ids: Sequence[int], epoch: int = 0) -> "RingMap":
        """The legacy placement as a ring map: equal members, vnodes=0
        — ``owners`` reproduces the pre-r14 cyclic mod-N sets
        byte-for-byte."""
        return RingMap(epoch=epoch, vnodes=0, members=tuple(
            RingMember(node_id=i) for i in sorted(node_ids)))

    @staticmethod
    def hashed(weights: dict[int, float], epoch: int,
               vnodes: int = DEFAULT_VNODES) -> "RingMap":
        """A weighted consistent-hash map from ``{node_id: weight}``."""
        return RingMap(epoch=epoch, vnodes=max(1, int(vnodes)),
                       members=tuple(
                           RingMember(node_id=i, weight=float(w))
                           for i, w in sorted(weights.items())))

    # ---- serialization (wire + disk) --------------------------------- #

    def to_dict(self) -> dict:
        return {"epoch": self.epoch, "vnodes": self.vnodes,
                "members": [{"nodeId": m.node_id, "weight": m.weight,
                             "vnodesSeed": m.vnodes_seed}
                            for m in self.members]}

    @staticmethod
    def from_dict(d) -> "RingMap":
        """Parse a wire/disk map; raises ValueError on malformed input
        (the propose_ring op answers an application error, never a
        traceback, on garbage)."""
        if not isinstance(d, dict):
            raise ValueError("ring map must be an object")
        try:
            members = tuple(
                RingMember(node_id=int(m["nodeId"]),
                           weight=float(m.get("weight", 1.0)),
                           vnodes_seed=int(m.get("vnodesSeed", 0)))
                for m in d.get("members", []))
            return RingMap(epoch=int(d["epoch"]),
                           vnodes=int(d.get("vnodes", 0)),
                           members=members)
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"malformed ring map: {e}") from e

    # ---- derived state ----------------------------------------------- #

    @property
    def key(self) -> tuple:
        """Cheap identity for memo keys (ec placement cache)."""
        return (self.epoch, self.vnodes,
                tuple((m.node_id, m.weight, m.vnodes_seed)
                      for m in self.members))

    @property
    def fingerprint(self) -> str:
        """Content hash of the map. Epochs alone cannot totally order
        maps: two admins racing on different nodes both build epoch+1
        from the same base and install DIFFERENT epoch-N maps — without
        a tiebreaker the two halves of the cluster would place by
        different owner maps forever while every epoch check passes.
        (epoch, fingerprint) is the total order every install and every
        wire-level mismatch check compares (docs/membership.md)."""
        cached = self.__dict__.get("_fingerprint")
        if cached is None:
            cached = sha256_hex(repr(self.key).encode())[:16]
            object.__setattr__(self, "_fingerprint", cached)
        return cached

    def active_ids(self) -> list[int]:
        """Sorted ids of members that own digest space (weight > 0)."""
        return sorted(m.node_id for m in self.members if m.weight > 0)

    def member_ids(self) -> list[int]:
        return sorted(m.node_id for m in self.members)

    def weight_of(self, node_id: int) -> float | None:
        for m in self.members:
            if m.node_id == node_id:
                return m.weight
        return None

    def _table(self) -> tuple[list[int], list[int]]:
        """(sorted vnode positions, member id per position) — built once
        per map instance. Positions colliding across members (a ~2^-64
        event per pair) keep the later-sorted entry; owners() walks by
        distinct member so the effect is one vnode's arc."""
        cached = self.__dict__.get("_ring_table")
        if cached is not None:
            return cached
        pts: list[tuple[int, int]] = []
        for m in self.members:
            # every ACTIVE member projects >= 1 vnode: a tiny positive
            # weight rounding to zero would make a member "active" yet
            # own nothing — owners() would silently return fewer than
            # rf nodes and every write would lose a replica with no
            # error anywhere (weight 0 = draining stays at zero)
            n = max(1, int(round(m.weight * self.vnodes))) \
                if m.weight > 0 else 0
            for i in range(n):
                pts.append((_point(f"{m.node_id}:{m.vnodes_seed}:{i}"),
                            m.node_id))
        pts.sort()
        table = ([p for p, _ in pts], [n for _, n in pts])
        # frozen dataclass: cache via __dict__ (not a field — identity
        # and serialization must not see it)
        object.__setattr__(self, "_ring_table", table)
        return table

    # ---- placement --------------------------------------------------- #

    def owners_at(self, point: int, rf: int) -> list[int]:
        """First ``rf`` distinct active members clockwise from
        ``point`` (hash mode only)."""
        pts, ids = self._table()
        if not pts:
            raise ValueError("empty ring")
        out: list[int] = []
        seen: set[int] = set()
        i = bisect.bisect_left(pts, point)
        n = len(pts)
        for k in range(n):
            nid = ids[(i + k) % n]
            if nid not in seen:
                seen.add(nid)
                out.append(nid)
                if len(out) >= rf:
                    break
        return out

    def owners(self, digest: str, rf: int) -> list[int]:
        """Owner node ids of a content digest, primary first. Static
        mode reproduces the legacy cyclic replica set exactly; hash
        mode walks the weighted ring. ``rf`` beyond the active member
        count is clamped (every active member is an owner)."""
        if self.vnodes == 0:
            return static_replica_set(digest, self.member_ids(), rf)
        active = self.active_ids()
        if not active:
            raise ValueError("empty ring")
        return self.owners_at(digest_point(digest), min(rf, len(active)))

    def owners_key(self, key: str, rf: int) -> list[int]:
        """Owners of an arbitrary string key (EC stripe bases, handoff
        walks) — hash mode only."""
        active = self.active_ids()
        if not active:
            raise ValueError("empty ring")
        return self.owners_at(_point(key), min(rf, len(active)))

    def ec_stripe_nodes(self, file_id: str, stripe: int,
                        nshards: int) -> list[int]:
        """Holder per shard (0..k-1 data, k = P, k+1 = Q) of one erasure
        stripe: ``nshards`` DISTINCT nodes — a single node loss must
        never cost two shards of a stripe (upload enforces
        k+2 <= active members). Static mode keeps the legacy
        consecutive fan-out; hash mode takes the first ``nshards``
        distinct members clockwise from the stripe's key point."""
        if self.vnodes == 0:
            ids = self.member_ids()
            return [static_ec_shard_node(file_id, stripe, j, ids)
                    for j in range(nshards)]
        out = self.owners_key(f"ec:{file_id}:{stripe}", nshards)
        if len(out) < nshards:
            raise ValueError(
                f"stripe needs {nshards} distinct nodes, ring walk "
                f"found {len(out)}")
        return out

    def ec_shard_node(self, file_id: str, stripe: int,
                      shard: int) -> int:
        return self.ec_stripe_nodes(file_id, stripe, shard + 1)[shard]

    def handoff_order(self, pinned: Sequence[int]) -> list[int]:
        """Agreed candidate order for a PINNED (EC) shard: its pinned
        holders, then the rest of the membership in a deterministic
        walk — the write side's sloppy-quorum handoff and the read
        side's candidate scan MUST agree on this order (the pre-r14
        placement.handoff_order contract, generalized)."""
        if self.vnodes == 0:
            return static_handoff_order(pinned, self.member_ids())
        active = self.active_ids()
        if not pinned:
            return list(active)
        walk = self.owners_key(f"pin:{pinned[0]}", len(active))
        return list(dict.fromkeys(list(pinned) + walk))


__all__ = ["DEFAULT_VNODES", "POINT_HEX", "RingMap", "RingMember",
           "digest_point", "static_ec_shard_node",
           "static_handoff_order", "static_replica_set"]
