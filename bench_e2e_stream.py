"""BASELINE.json configs[2]: a GiB-class synthetic stream through the
flagship fragmenter END TO END (staging + device chain + collection, via
the bounded-memory streaming walk — not the resident-kernel metric
bench.py records). On this harness the shared device tunnel's bandwidth
swings ~50x hour to hour, so the number is recorded for honesty with the
staging bandwidth measured alongside; the CPU engine's number is printed
for comparison (it is what `auto` falls back to when the link is slow).

Prints ONE JSON line:
    {"metric": "e2e_stream_chunk_hash_1GiB", "value": N, "unit": "GiB/s",
     "vs_baseline": N}
vs_baseline: against the native CPU engine on the same stream (>1 means
the device path beats CPU end to end on this link, i.e. `auto` would
rightly pick it).

Usage: python bench_e2e_stream.py [total_bytes] [backend: tpu|cpu|both]
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_blocks(total: int, block: int = 8 * 1024 * 1024,
                seed: int = 5) -> list[bytes]:
    """Pre-generated blocks (random with repeated sections, tarball-ish):
    corpus synthesis must not land inside the timed stream."""
    rng = np.random.default_rng(seed)
    rep = rng.integers(0, 256, size=block, dtype=np.uint8).tobytes()
    out = []
    done = 0
    i = 0
    while done < total:
        n = min(block, total - done)
        out.append(rep[:n] if i % 3 == 2
                   else rng.integers(0, 256, size=n,
                                     dtype=np.uint8).tobytes())
        done += n
        i += 1
    return out


def run(frag, blocks: list[bytes]) -> tuple[float, int]:
    total = sum(len(b) for b in blocks)
    t0 = time.perf_counter()
    m = frag.manifest_stream(iter(blocks), name="e2e")
    dt = time.perf_counter() - t0
    assert m.size == total
    return dt, m.total_chunks


def probe_link(reps: int = 3) -> float:
    """Staging bandwidth at the WALK's transfer size (one region
    buffer), fresh arrays, best of ``reps`` — the link number the
    device path is honestly comparable against (the 8 MiB probe `auto`
    uses measures up to ~3x faster on this tunnel)."""
    import jax

    from dfs_tpu.ops.cdc_anchored import (AnchoredCdcParams,
                                          region_buffer_size)

    rb = region_buffer_size(64 * 1024 * 1024, AnchoredCdcParams())
    buf = np.zeros(rb, dtype=np.uint8)
    jax.block_until_ready(jax.device_put(buf))      # warm the path
    best = float("inf")
    for _ in range(reps):
        fresh = buf.copy()
        t0 = time.perf_counter()
        jax.block_until_ready(jax.device_put(fresh))
        best = min(best, time.perf_counter() - t0)
    return rb / best


def main() -> int:
    total = int(sys.argv[1]) if len(sys.argv) > 1 else 1024 * 1024 * 1024
    backend = sys.argv[2] if len(sys.argv) > 2 else "both"

    from dfs_tpu.fragmenter.cdc_anchored import (AnchoredCpuFragmenter,
                                                 AnchoredTpuFragmenter)

    blocks = make_blocks(total)
    warm = make_blocks(128 * 1024 * 1024, seed=9)

    cpu_dt = None
    if backend in ("cpu", "both"):
        cpu = AnchoredCpuFragmenter()
        run(cpu, warm)                           # warm the native lib
        cpu_dt, n = run(cpu, blocks)
        log(f"cpu anchored: {total / cpu_dt / 2**30:.3f} GiB/s "
            f"({cpu_dt:.1f}s, {n} chunks)")

    if backend == "cpu":
        gibps = total / cpu_dt / 2**30
        print(json.dumps({"metric": "e2e_stream_chunk_hash_cpu",
                          "value": round(gibps, 3), "unit": "GiB/s",
                          "vs_baseline": 1.0}))
        return 0

    tpu = AnchoredTpuFragmenter()
    run(tpu, warm)                               # compile + warm transfers
    link_before = probe_link()
    tpu.reset_staging_samples()                  # scope to the timed run
    tpu_dt, n = run(tpu, blocks)
    observed = tpu.staging_observed_bw() or 0.0  # the link the walk HAD:
    # its own timed window transfers, concurrent with the run — the only
    # number comparable to e2e on a tunnel that swings 50x per minute
    # (bracket probes taken seconds away routinely disagree 3-5x)
    link_after = probe_link()
    tpu_gibps = total / tpu_dt / 2**30
    timed_windows = tpu.staging_timed_windows()
    log(f"tpu anchored (streamed): {tpu_gibps:.3f} GiB/s "
        f"({tpu_dt:.1f}s, {n} chunks); staging link: in-walk observed "
        f"{observed / 2**30:.3f} GiB/s over "
        f"{timed_windows} timed windows (bracket probes "
        f"{link_before / 2**30:.3f} / {link_after / 2**30:.3f}) -> "
        f"device path at {tpu_gibps / max(observed / 2**30, 1e-9):.2f}x "
        f"its observed link")

    # the recorded metric is the PRODUCTION path: `auto` probes staging
    # bandwidth once and picks device vs native-CPU engine (what a node
    # started with the default fragmenter actually ingests at on this
    # link, fragmenter/base.py:tpu_available) — the explicit device and
    # CPU numbers above are the diagnostic split
    from dfs_tpu.fragmenter.base import get_fragmenter
    auto = get_fragmenter("auto")
    log(f"auto picked: {auto.name}")
    run(auto, warm)
    auto_dt, n = run(auto, blocks)
    gibps = total / auto_dt / 2**30
    log(f"auto (streamed): {gibps:.3f} GiB/s ({auto_dt:.1f}s, {n} chunks)")
    vs = (cpu_dt / auto_dt) if cpu_dt else 1.0
    print(json.dumps({
        "metric": "e2e_stream_chunk_hash_1GiB_auto",
        "value": round(gibps, 3), "unit": "GiB/s",
        "vs_baseline": round(vs, 3),
        "engines": {
            "device_gibps": round(tpu_gibps, 4),
            "cpu_gibps": round(total / cpu_dt / 2**30, 4) if cpu_dt
            else None,
            "auto_picked": auto.name,
        },
        "staging_link": {
            "in_walk_observed_gibps": round(observed / 2**30, 4),
            "in_walk_timed_windows": timed_windows,
            "probe_before_gibps": round(link_before / 2**30, 4),
            "probe_after_gibps": round(link_after / 2**30, 4),
            "probe": "region-buffer-sized fresh device_put, best of 3; "
                     "in-walk = the walk's own timed window transfers "
                     "(concurrent with the run)",
            "device_vs_link": round(
                tpu_gibps / max(observed / 2**30, 1e-9), 3),
        }}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
