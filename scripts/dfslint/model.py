"""Phase 1 of the interprocedural analyzer: the whole-repo model.

The r08 rules are single-function AST visitors; three review rounds
each hand-caught a bug class they structurally cannot see (the r13
ManifestStore resurrection race, the r15 staging-buffer
recycle-while-in-flight aliasing, client/handler wire drift). This
module builds the facts those bug classes are *about*, once per run,
shared by every pass:

- a **module-qualified call graph** over the walked sources (imports,
  same-module calls, ``self.method`` calls, and ``self.attr.method``
  calls through constructor-/annotation-derived attribute types);
- an **execution-context inference**: every function is classified on
  the lattice ``{} ⊂ {loop} | {worker} ⊂ {loop, worker}`` — seeded
  from ``async def`` (loop), executor/thread dispatch sites
  (``asyncio.to_thread``, ``run_in_executor``, ``pool.submit``,
  ``Thread(target=…)`` → worker), loop-marshalled callbacks
  (``call_soon_threadsafe``, ``add_done_callback`` → loop), and
  executor *trampolines* (a function whose parameter reaches a
  dispatch site — ``AsyncChunkStore._run`` — seeds its call sites'
  callable arguments as worker entry points), then propagated along
  sync call edges to a fixed point;
- a **symbol table of ``self.<attr>`` accesses**: per (class, attr),
  every read/write with the set of lock-ish ``with`` guards held at
  the access — the facts DFS008's affinity-race check joins against
  the context classification;
- the set of functions that **return borrowed buffer views**
  (``memoryview``/``unpack_chunks``-derived), so DFS009 can follow a
  view through one call without type inference.

Everything here is a best-effort lexical approximation — unresolvable
calls simply contribute no edge, and an unknown context is the empty
set (which no rule fires on). That bias is deliberate: phase 2 rules
must only fire on facts the model actually established.
"""

from __future__ import annotations

import ast
import dataclasses
import re

from scripts.dfslint.core import Project, SourceFile, dotted, scope_nodes

LOOP = "loop"
WORKER = "worker"

# `with <expr>:` guards treated as locks. Wider than DFS003's _LOCKISH
# on purpose: the store layer names its ordering mutexes `_index_mu` /
# `_mu` and the model must see them as guards, not as unprotected
# accesses.
LOCKISH = re.compile(
    r"(lock|mutex|mtx|cond|sem(aphore)?|(^|_)mu$|(^|_)cv$)",
    re.IGNORECASE)

# executor dispatch shapes: (callable-position args, target= keyword)
_THREAD_SEED_ATTRS = frozenset({"submit"})
# callables marshalled BACK to the event loop from anywhere. NOT
# add_done_callback: on a concurrent.futures future the callback runs
# on the POOL WORKER thread (store/aio.py uses exactly those), so
# seeding it loop would invert DFS003/DFS008's analysis — unknown
# context is the honest classification there.
_LOOP_CALLBACK_ATTRS = frozenset({"call_soon_threadsafe", "call_soon"})

# mutating method names: a call `self.x.append(...)` is a WRITE to the
# shared structure behind `self.x`, not a read
_MUTATOR_ATTRS = frozenset({
    "add", "append", "appendleft", "extend", "insert", "remove",
    "discard", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "put_nowait", "push", "rotate",
})

# calls that return a borrowed view of an existing buffer
_VIEW_CALLS = frozenset({"memoryview", "unpack_chunks"})
_VIEW_METHODS = frozenset({"toreadonly", "cast", "getbuffer"})


@dataclasses.dataclass
class FuncInfo:
    """One function/method/lambda in the walked project."""

    uid: str                 # "<rel>:<qualname>:<lineno>" — unique
    src: SourceFile
    node: ast.AST            # FunctionDef | AsyncFunctionDef | Lambda
    name: str
    cls: str | None          # nearest enclosing ClassDef name
    is_async: bool
    params: tuple[str, ...]
    ctx: set = dataclasses.field(default_factory=set)
    callees: set = dataclasses.field(default_factory=set)  # uids
    returns_view: bool = False

    @property
    def qual(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


@dataclasses.dataclass(frozen=True)
class AttrAccess:
    """One ``self.<attr>`` touch inside a method."""

    cls: str
    attr: str
    kind: str                # "read" | "write"
    fn: FuncInfo
    node: ast.AST
    locks: frozenset        # lock-ish guard names held at the access


def _param_names(node: ast.AST) -> tuple[str, ...]:
    a = getattr(node, "args", None)
    if a is None:
        return ()
    names = [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return tuple(names)


def lock_names(expr: ast.AST) -> str | None:
    """Guard name for a ``with <expr>`` item when it is lock-ish.
    Handles plain names (``self._lock``), factory calls
    (``self._lock_for(fid)``, ``threading.Lock()``), and subscripts of
    lock arrays (``self._mu[i]`` — the striped-lock idiom)."""
    base = expr
    if isinstance(base, ast.Call):
        base = base.func
    if isinstance(base, ast.Subscript):
        base = base.value
    name = dotted(base)
    if name and LOCKISH.search(name.split(".")[-1]):
        return name
    return None


class ProjectModel:
    """The phase-1 facts. Build once via :func:`build_model`; every
    phase-2 rule reads it (``Project.model`` caches it)."""

    def __init__(self, project: Project) -> None:
        self.project = project
        self.functions: dict[str, FuncInfo] = {}
        # resolution tables
        self._by_module_func: dict[tuple[str, str], FuncInfo] = {}
        self._by_class_method: dict[tuple[str, str], FuncInfo] = {}
        self._attr_types: dict[tuple[str, str], str] = {}
        self._imports: dict[str, dict[str, str]] = {}
        self._fn_of_node: dict[tuple[int, int], FuncInfo] = {}
        # per-function name -> nested FuncInfo (computed once: the
        # per-call ast.walk search was quadratic on runtime.py)
        self._nested: dict[str, dict[str, FuncInfo]] = {}
        # callee uid -> [(caller uid, locks held at the call site)] —
        # feeds the inherited-lock fixed point (the `*_locked` caller-
        # holds-the-lock convention becomes a model fact)
        self._call_sites: dict[str, list[tuple[str, frozenset]]] = {}
        self._inherited_locks: dict[str, frozenset] = {}
        # per-function Call nodes in scope (filled by the edge pass)
        self._calls_of: dict[str, list[ast.Call]] = {}
        self._view_stmt_cache: dict[str, list[ast.AST]] = {}
        # per-function locals known to OWN their buffer (assigned from
        # bytes()/bytearray()): a memoryview over one is not borrowed
        self._owned_vars: dict[str, set[str]] = {}
        self.accesses: dict[tuple[str, str], list[AttrAccess]] = {}
        self._build()

    # ---- construction ------------------------------------------------- #

    @staticmethod
    def _module_of(rel: str) -> str:
        mod = rel[:-3] if rel.endswith(".py") else rel
        mod = mod.replace("/", ".")
        return mod[:-9] if mod.endswith(".__init__") else mod

    def _build(self) -> None:
        for src in self.project.files:
            if src.tree is None:
                continue
            self._collect_functions(src)
            self._collect_imports(src)
        pending: list[tuple[FuncInfo, ast.Attribute, ast.AST]] = []
        for src in self.project.files:
            if src.tree is None:
                continue
            pending.extend(self._collect_attr_assigns(src))
        self._resolve_attr_types(pending)
        seeds: list[tuple[FuncInfo, str]] = []
        for src in self.project.files:
            if src.tree is None:
                continue
            seeds.extend(self._collect_edges_and_seeds(src))
        # trampolines: a fn whose param reaches a dispatch site makes
        # every callable argument at its call sites a worker entry
        seeds.extend(self._trampoline_seeds())
        self._propagate(seeds)
        self._compute_inherited_locks()
        self._collect_accesses()
        self._compute_returns_view()

    def _collect_functions(self, src: SourceFile) -> None:
        mod = self._module_of(src.rel)
        fns = src.nodes(ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        created: list[FuncInfo] = []
        # pass 1: create + register every FuncInfo (the node index is
        # grouped by TYPE, so a nested sync def can precede its async
        # parent — enclosing-scope lookups must wait for pass 2)
        for node in fns:
            name = getattr(node, "name", "<lambda>")
            cls = None
            cur = src.parents.get(node)
            while cur is not None:
                if isinstance(cur, ast.ClassDef):
                    cls = cur.name
                    break
                cur = src.parents.get(cur)
            fi = FuncInfo(
                uid=f"{src.rel}:{src.qualname(node)}.{name}"
                    f":{node.lineno}",
                src=src, node=node, name=name, cls=cls,
                is_async=isinstance(node, ast.AsyncFunctionDef),
                params=_param_names(node))
            if fi.is_async:
                fi.ctx.add(LOOP)
            self.functions[fi.uid] = fi
            self._fn_of_node[(id(src), id(node))] = fi
            created.append(fi)
        # pass 2: nesting + name tables (every function resolvable now)
        for fi in created:
            if isinstance(fi.node, ast.Lambda):
                continue
            encl = self._enclosing_fn(src, fi.node)
            if encl is not None:
                self._nested.setdefault(encl.uid, {})[fi.name] = fi
            parent = src.parents.get(fi.node)
            if isinstance(parent, ast.Module):
                self._by_module_func.setdefault((mod, fi.name), fi)
            elif isinstance(parent, ast.ClassDef) \
                    and src.parents.get(parent) is not None:
                self._by_class_method[(parent.name, fi.name)] = fi

    def _collect_imports(self, src: SourceFile) -> None:
        table: dict[str, str] = {}
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    table[alias.asname or alias.name.split(".")[0]] = \
                        alias.name
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for alias in node.names:
                    table[alias.asname or alias.name] = \
                        f"{node.module}.{alias.name}"
        self._imports[src.rel] = table

    def _known_class(self, name: str | None) -> str | None:
        if name is None:
            return None
        last = name.split(".")[-1]
        return last if any(last == c for c, _ in self._by_class_method) \
            else None

    def _collect_attr_assigns(self, src: SourceFile
                              ) -> list[tuple[FuncInfo, ast.Attribute,
                                              ast.AST]]:
        """Every ``self.…x = value`` site, for the attr-type pass."""
        out: list[tuple[FuncInfo, ast.Attribute, ast.AST]] = []
        for node in src.nodes(ast.Assign, ast.AnnAssign):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                t, value = node.target, node.value
            else:
                continue
            if not isinstance(t, ast.Attribute):
                continue
            fn = self._enclosing_fn(src, node)
            if fn is None or fn.cls is None:
                continue
            out.append((fn, t, value))
        return out

    def _resolve_attr_types(self, pending: list) -> None:
        """``self.x = SomeClass(...)`` and ``self.x = <param annotated
        SomeClass>`` pin the attribute's class, so ``self.x.m()``
        resolves module-qualified instead of by name-guess. Chained
        targets resolve through already-known types to a fixed point —
        the runtime's seam wiring (``self.store.chunks.index =
        IndexPlane(...)``) types ``ChunkStore.index`` even though the
        assignment lives in another class and another file."""
        for _ in range(4):
            progressed = False
            for fn, t, value in pending:
                owner = self._owner_class(fn, t.value)
                if owner is None or (owner, t.attr) in self._attr_types:
                    continue
                cls_name = None
                if isinstance(value, ast.Call):
                    cls_name = self._known_class(dotted(value.func))
                elif isinstance(value, ast.Name):
                    if fn is not None:
                        ann = self._param_annotation(fn, value.id)
                        cls_name = self._known_class(ann)
                elif isinstance(value, ast.Attribute):
                    got = self._owner_class(fn, value.value)
                    if got is not None:
                        cls_name = self._attr_types.get(
                            (got, value.attr))
                if cls_name:
                    self._attr_types[(owner, t.attr)] = cls_name
                    progressed = True
            if not progressed:
                break

    def _owner_class(self, fn: FuncInfo, expr: ast.AST) -> str | None:
        """Class owning the attribute at the END of a ``self.a.b…``
        chain (``self`` → the method's own class; each hop through the
        attr-type table)."""
        chain = dotted(expr)
        if chain is None or fn.cls is None:
            return None
        parts = chain.split(".")
        if parts[0] != "self":
            return None
        cls = fn.cls
        for attr in parts[1:]:
            cls = self._attr_types.get((cls, attr))
            if cls is None:
                return None
        return cls

    @staticmethod
    def _param_annotation(fn: FuncInfo, pname: str) -> str | None:
        a = getattr(fn.node, "args", None)
        if a is None:
            return None
        for p in (*a.posonlyargs, *a.args, *a.kwonlyargs):
            if p.arg == pname and p.annotation is not None:
                return dotted(p.annotation)
        return None

    def _enclosing_fn(self, src: SourceFile,
                      node: ast.AST) -> FuncInfo | None:
        cur = src.parents.get(node)
        while cur is not None:
            fi = self._fn_of_node.get((id(src), id(cur)))
            if fi is not None:
                return fi
            cur = src.parents.get(cur)
        return None

    # ---- call/target resolution ---------------------------------------- #

    def resolve_call(self, src: SourceFile, fn: FuncInfo | None,
                     call_func: ast.AST) -> FuncInfo | None:
        """Best-effort resolution of a call expression to a FuncInfo."""
        # self.method(...) / self.attr.method(...)
        if isinstance(call_func, ast.Attribute):
            chain = dotted(call_func)
            if chain and chain.startswith("self.") and fn is not None \
                    and fn.cls is not None:
                parts = chain.split(".")
                cls: str | None = fn.cls
                for attr in parts[1:-1]:
                    cls = self._attr_types.get((cls, attr))
                    if cls is None:
                        return None
                return self._by_class_method.get((cls, parts[-1]))
            # mod.func(...) via imports
            if chain:
                head, _, tail = chain.partition(".")
                imp = self._imports.get(src.rel, {}).get(head)
                if imp is not None and "." not in tail:
                    return self._by_module_func.get((imp, tail)) \
                        or self._by_class_method.get(
                            (imp.split(".")[-1], tail))
            return None
        if isinstance(call_func, ast.Name):
            name = call_func.id
            # nested function in the lexically-enclosing chain
            cur = fn
            while cur is not None:
                got = self._nested.get(cur.uid, {}).get(name)
                if got is not None:
                    return got
                cur = self._enclosing_fn(src, cur.node)
            mod = self._module_of(src.rel)
            got = self._by_module_func.get((mod, name))
            if got is not None:
                return got
            imp = self._imports.get(src.rel, {}).get(name)
            if imp is not None:
                pmod, _, pname = imp.rpartition(".")
                return self._by_module_func.get((pmod, pname))
        return None

    def _resolve_target(self, src: SourceFile, fn: FuncInfo | None,
                        expr: ast.AST) -> FuncInfo | None:
        """A callable ARGUMENT (dispatch target / callback): a lambda,
        a local/nested/module function name, or ``self.method``."""
        if isinstance(expr, ast.Lambda):
            return self._fn_of_node.get((id(src), id(expr)))
        return self.resolve_call(src, fn, expr)

    def dispatch_targets(self, src: SourceFile, node: ast.Call
                         ) -> tuple[list[ast.AST], list[ast.AST]]:
        """(worker-seeded exprs, loop-seeded exprs) for one call."""
        workers: list[ast.AST] = []
        loops: list[ast.AST] = []
        name = dotted(node.func)
        if name == "asyncio.to_thread" and node.args:
            workers.append(node.args[0])
        elif isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr == "run_in_executor" and len(node.args) >= 2:
                workers.append(node.args[1])
            elif attr in _THREAD_SEED_ATTRS and node.args:
                workers.append(node.args[0])
            elif attr == "Thread":
                kw = next((k.value for k in node.keywords
                           if k.arg == "target"), None)
                if kw is not None:
                    workers.append(kw)
            elif attr in _LOOP_CALLBACK_ATTRS and node.args:
                loops.append(node.args[0])
        if name in ("threading.Thread", "Thread"):
            kw = next((k.value for k in node.keywords
                       if k.arg == "target"), None)
            if kw is not None:
                workers.append(kw)
        return workers, loops

    def _collect_edges_and_seeds(self, src: SourceFile
                                 ) -> list[tuple[FuncInfo, str]]:
        """One pass over the file's Call nodes: each call belongs to
        its IMMEDIATE enclosing function (the same not-into-nested-
        scopes semantics scope_nodes gives, without re-walking every
        function subtree)."""
        seeds: list[tuple[FuncInfo, str]] = []
        for n in src.nodes(ast.Call):
            fi = self._enclosing_fn(src, n)
            if fi is not None:
                self._calls_of.setdefault(fi.uid, []).append(n)
            workers, loops = self.dispatch_targets(src, n)
            for expr in workers:
                tgt = self._resolve_target(src, fi, expr)
                if tgt is not None:
                    seeds.append((tgt, WORKER))
            for expr in loops:
                tgt = self._resolve_target(src, fi, expr)
                if tgt is not None:
                    seeds.append((tgt, LOOP))
            if workers or loops or fi is None:
                continue  # dispatch, not a same-context call edge
            callee = self.resolve_call(src, fi, n.func)
            if callee is not None:
                fi.callees.add(callee.uid)
                self._call_sites.setdefault(callee.uid, []).append(
                    (fi.uid, self._locks_held(src, n, fi.node)))
        return seeds

    def _trampoline_seeds(self) -> list[tuple[FuncInfo, str]]:
        """``AsyncChunkStore._run(pool, fn)`` shape: ``fn`` (a param)
        reaches ``run_in_executor`` — possibly via a nested def that
        calls it — so callable args at ``_run``'s call sites run on
        worker threads."""
        tramp: dict[str, set[str]] = {}
        for fi in self.functions.values():
            if isinstance(fi.node, ast.Lambda) or not fi.params:
                continue
            dispatched: set[str] = set()
            for n in self._calls_of.get(fi.uid, ()):
                workers, _ = self.dispatch_targets(fi.src, n)
                for expr in workers:
                    if isinstance(expr, ast.Name):
                        dispatched.add(expr.id)
            if not dispatched:
                continue
            params = set(fi.params)
            hit = dispatched & params
            for name, nested in self._nested.get(fi.uid, {}).items():
                if name in dispatched:
                    called = {c.func.id
                              for c in self._calls_of.get(nested.uid, ())
                              if isinstance(c.func, ast.Name)}
                    hit |= called & params
            if hit:
                tramp[fi.uid] = hit
        if not tramp:
            return []
        seeds: list[tuple[FuncInfo, str]] = []
        for fi in self.functions.values():
            src = fi.src
            for n in self._calls_of.get(fi.uid, ()):
                callee = self.resolve_call(src, fi, n.func)
                if callee is None or callee.uid not in tramp:
                    continue
                pnames = tramp[callee.uid]
                # positional args map onto the callee's params
                # (skipping its leading self for bound-method calls)
                params = list(callee.params)
                if params and params[0] == "self":
                    params = params[1:]
                for i, arg in enumerate(n.args):
                    if i < len(params) and params[i] in pnames:
                        tgt = self._resolve_target(src, fi, arg)
                        if tgt is not None:
                            seeds.append((tgt, WORKER))
                for kw in n.keywords:
                    if kw.arg in pnames:
                        tgt = self._resolve_target(src, fi, kw.value)
                        if tgt is not None:
                            seeds.append((tgt, WORKER))
        return seeds

    def _propagate(self, seeds: list[tuple[FuncInfo, str]]) -> None:
        work: list[FuncInfo] = []
        for fi, ctx in seeds:
            if ctx not in fi.ctx:
                fi.ctx.add(ctx)
            work.append(fi)
        work.extend(fi for fi in self.functions.values() if fi.ctx)
        while work:
            fi = work.pop()
            for uid in fi.callees:
                callee = self.functions.get(uid)
                if callee is None:
                    continue
                add = fi.ctx - callee.ctx
                if callee.is_async:
                    # an async callee always runs on the loop; a worker
                    # caller cannot await it, so worker never crosses
                    add = add & {LOOP}
                if add:
                    callee.ctx |= add
                    work.append(callee)

    # ---- symbol table -------------------------------------------------- #

    def _compute_inherited_locks(self) -> None:
        """Locks a function can RELY on its callers holding: the
        intersection, over every resolved call site, of the locks held
        lexically at the site plus the caller's own inherited set — the
        ``_flush_wal_locked`` convention (callee runs with the store
        lock held) established as a fact instead of trusted by name.
        A function with no resolved call sites inherits nothing."""
        inh: dict[str, frozenset] = {}
        for _ in range(8):
            changed = False
            for callee, sites in self._call_sites.items():
                new = None
                for caller, locks in sites:
                    held = locks | inh.get(caller, frozenset())
                    new = held if new is None else (new & held)
                new = new or frozenset()
                if inh.get(callee, frozenset()) != new:
                    inh[callee] = new
                    changed = True
            if not changed:
                break
        self._inherited_locks = inh

    def inherited_locks(self, fn: FuncInfo) -> frozenset:
        return self._inherited_locks.get(fn.uid, frozenset())

    def callers_of(self, fn: FuncInfo) -> list[FuncInfo]:
        """Every function with a resolved call site into ``fn``."""
        return [self.functions[c]
                for c, _ in self._call_sites.get(fn.uid, [])
                if c in self.functions]

    def _locks_held(self, src: SourceFile, node: ast.AST,
                    stop: ast.AST) -> frozenset:
        held: set[str] = set()
        cur = src.parents.get(node)
        while cur is not None and cur is not stop:
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                for item in cur.items:
                    name = lock_names(item.context_expr)
                    if name:
                        held.add(name)
            cur = src.parents.get(cur)
        return frozenset(held)

    def _collect_accesses(self) -> None:
        for src in self.project.files:
            if src.tree is None:
                continue
            for n in src.nodes(ast.Attribute):
                if not (isinstance(n.value, ast.Name)
                        and n.value.id == "self"):
                    continue
                fi = self._enclosing_fn(src, n)
                if fi is None or fi.cls is None:
                    continue
                acc = self._classify_access(src, n)
                if acc is None:
                    continue
                attr, kind, anchor = acc
                held = self._locks_held(src, anchor, fi.node) \
                    | self.inherited_locks(fi)
                self.accesses.setdefault((fi.cls, attr), []).append(
                    AttrAccess(fi.cls, attr, kind, fi, anchor, held))

    def _classify_access(self, src: SourceFile, n: ast.AST
                         ) -> tuple[str, str, ast.AST] | None:
        if isinstance(n, ast.Attribute) and isinstance(n.value, ast.Name) \
                and n.value.id == "self":
            parent = src.parents.get(n)
            if isinstance(n.ctx, (ast.Store, ast.Del)):
                return n.attr, "write", n
            if isinstance(parent, ast.AugAssign) and parent.target is n:
                return n.attr, "write", n
            # self.x[k] = v / del self.x[k]
            if isinstance(parent, ast.Subscript) \
                    and isinstance(parent.ctx, (ast.Store, ast.Del)):
                return n.attr, "write", n
            # self.x.append(...) and friends mutate the structure
            if isinstance(parent, ast.Attribute) \
                    and parent.attr in _MUTATOR_ATTRS:
                gp = src.parents.get(parent)
                if isinstance(gp, ast.Call) and gp.func is parent:
                    return n.attr, "write", n
            return n.attr, "read", n
        return None

    # ---- view-returning functions -------------------------------------- #

    def _compute_returns_view(self) -> None:
        # only functions that actually return something participate
        returners = []
        for fi in self.functions.values():
            if isinstance(fi.node, ast.Lambda):
                continue
            rets = [n for n in scope_nodes(fi.node)
                    if isinstance(n, ast.Return) and n.value is not None]
            if rets:
                returners.append((fi, rets))
        changed = True
        while changed:
            changed = False
            for fi, rets in returners:
                if fi.returns_view:
                    continue
                views = view_vars(self, fi)
                if any(is_view_expr(self, fi, r.value, views)
                       for r in rets):
                    fi.returns_view = True
                    changed = True

    def fn_for(self, src: SourceFile, node: ast.AST) -> FuncInfo | None:
        return self._fn_of_node.get((id(src), id(node)))


# ---- shared view dataflow (used by the model and DFS009) -------------- #

# self-attribute names that denote POOLED/recycled buffers: a view over
# one is only valid until the pool recycles it (the r15 bug class). The
# naming heuristic is the same contract as DFS003's lock regex: name
# pooled buffers like pooled buffers.
POOLED_ATTR = re.compile(r"(staging|pool|scratch|recycl|spare|arena)",
                         re.IGNORECASE)


def is_view_source_call(model: ProjectModel, fn: FuncInfo,
                        call: ast.Call, views: set[str]) -> bool:
    name = dotted(call.func)
    if name in _VIEW_CALLS or (
            name and name.split(".")[-1] in _VIEW_CALLS):
        if name and name.split(".")[-1] == "memoryview" and call.args:
            return _borrowed_base(call.args[0], views,
                                  model._owned_vars.get(fn.uid, set()))
        return True
    if isinstance(call.func, ast.Attribute):
        if call.func.attr in _VIEW_METHODS:
            return True
        # one interprocedural hop: a call to a function the model
        # knows returns a view
    resolved = model.resolve_call(fn.src, fn, call.func)
    return resolved is not None and resolved.returns_view


def _borrowed_base(expr: ast.AST, views: set[str],
                   owned: set[str] = frozenset()) -> bool:
    """Is ``memoryview(expr)`` a view over memory this function does
    NOT own? Owned: a fresh local ``bytes``/``bytearray`` (inline or a
    local name assigned from one — ``owned`` is the dataflow set
    ``view_vars`` maintains) or a plain ``self.<attr>`` buffer —
    UNLESS the attr name marks it pooled (staging/pool/scratch/…),
    where recycling is the whole point."""
    if isinstance(expr, ast.Call):
        name = dotted(expr.func)
        if name in ("bytes", "bytearray"):
            return False
        return True
    chain = dotted(expr)
    if chain and chain.startswith("self."):
        return bool(POOLED_ATTR.search(chain))
    if isinstance(expr, ast.Name):
        # param or local of unknown provenance: borrowed — unless the
        # forward pass saw it assigned from a fresh bytes/bytearray
        return expr.id not in owned
    if isinstance(expr, ast.Subscript):
        return _borrowed_base(expr.value, views, owned)
    return True


def is_view_expr(model: ProjectModel, fn: FuncInfo, expr: ast.AST,
                 views: set[str]) -> bool:
    """Does ``expr`` evaluate to a borrowed view (given the known
    view-variable set)?"""
    if isinstance(expr, ast.Await):
        return is_view_expr(model, fn, expr.value, views)
    if isinstance(expr, ast.Name):
        return expr.id in views
    if isinstance(expr, ast.Call):
        return is_view_source_call(model, fn, expr, views)
    if isinstance(expr, ast.Subscript):
        return is_view_expr(model, fn, expr.value, views)
    if isinstance(expr, ast.Attribute):
        # v.obj / v.field — views of views only via the known methods
        return False
    if isinstance(expr, ast.IfExp):
        return is_view_expr(model, fn, expr.body, views) \
            or is_view_expr(model, fn, expr.orelse, views)
    return False


def view_vars(model: ProjectModel, fn: FuncInfo) -> set[str]:
    """Names bound to borrowed views inside ``fn`` (forward pass in
    line order; a later rebind to a copy — ``v = bytes(v)`` — clears
    the mark)."""
    views: set[str] = set()
    if isinstance(fn.node, ast.Lambda):
        return views
    # live reference: is_view_expr consults it mid-pass via the model
    owned = model._owned_vars.setdefault(fn.uid, set())
    owned.clear()
    stmts = model._view_stmt_cache.get(fn.uid)
    if stmts is None:
        stmts = sorted((n for n in scope_nodes(fn.node)
                        if isinstance(n, (ast.Assign, ast.AnnAssign,
                                          ast.For, ast.AsyncFor))),
                       key=lambda n: (n.lineno, n.col_offset))
        model._view_stmt_cache[fn.uid] = stmts
    for st in stmts:
        if isinstance(st, (ast.For, ast.AsyncFor)):
            if is_view_expr(model, fn, st.iter, views):
                for t in ast.walk(st.target):
                    if isinstance(t, ast.Name):
                        views.add(t.id)
            continue
        value = st.value
        if value is None:
            continue
        targets = st.targets if isinstance(st, ast.Assign) else [st.target]
        is_view = is_view_expr(model, fn, value, views)
        owns = isinstance(value, ast.Call) \
            and dotted(value.func) in ("bytes", "bytearray")
        for t in targets:
            if isinstance(t, ast.Name):
                (views.add if is_view else views.discard)(t.id)
                (owned.add if owns else owned.discard)(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)) and is_view:
                # unpacking a view-producing call (unpack_chunks pairs,
                # conn.reply() triples): every bound name may borrow
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        views.add(sub.id)
    return views


def build_model(project: Project) -> ProjectModel:
    """Build (or return the cached) phase-1 model for ``project``."""
    cached = getattr(project, "_model", None)
    if cached is None:
        cached = ProjectModel(project)
        project._model = cached
    return cached
