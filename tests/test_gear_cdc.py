"""Gear CDC correctness: the parallel windowed bitmap (NumPy and JAX) must
match the sequential rolling-hash specification bit-for-bit, and chunking must
reconstruct byte-identically (north star: BASELINE.json)."""

import numpy as np
import jax.numpy as jnp

from dfs_tpu.config import CDCParams
from dfs_tpu.fragmenter.cdc_cpu import (
    CpuCdcFragmenter,
    cdc_cuts_ref,
    gear_bitmap_numpy,
    gear_hashes_seq,
)
from dfs_tpu.fragmenter.cdc_tpu import TpuCdcFragmenter
from dfs_tpu.ops.gear_jax import HALO, gear_hashes_dense
from dfs_tpu.utils.hashing import gear_table

PARAMS = CDCParams(min_size=64, avg_size=256, max_size=1024)
SMALL = CDCParams(min_size=32, avg_size=64, max_size=256)


def _corpora(rng):
    return {
        "random": rng.integers(0, 256, size=20_000, dtype=np.uint8).tobytes(),
        "zeros": bytes(5_000),
        "repeat": b"abcdefgh" * 2_000,
        "short": b"xyz",
        "empty": b"",
        "window": bytes(rng.integers(0, 256, size=31, dtype=np.uint8)),
    }


def test_windowed_equals_rolling(rng):
    """The core identity: 32-byte windowed sum == sequential rolling hash."""
    table = gear_table()
    data = rng.integers(0, 256, size=4_096, dtype=np.uint8)
    seq = gear_hashes_seq(data.tobytes(), table)
    dense = np.asarray(gear_hashes_dense(
        jnp.asarray(data), jnp.zeros((HALO,), jnp.uint32), jnp.asarray(table)))
    np.testing.assert_array_equal(seq, dense)


def test_numpy_bitmap_matches_rolling(rng):
    table = gear_table()
    data = rng.integers(0, 256, size=8_192, dtype=np.uint8)
    seq = gear_hashes_seq(data.tobytes(), table)
    mask = PARAMS.mask
    np.testing.assert_array_equal(
        (seq & mask) == 0, gear_bitmap_numpy(data, table, mask))


def test_cpu_cuts_match_reference_spec(rng):
    frag = CpuCdcFragmenter(PARAMS)
    for name, data in _corpora(rng).items():
        got = frag.cuts(data).tolist()
        want = cdc_cuts_ref(data, PARAMS)
        assert got == want, f"corpus {name}: {got[:5]} != {want[:5]}"


def test_tpu_cuts_match_cpu(rng):
    cpu = CpuCdcFragmenter(PARAMS)
    tpu = TpuCdcFragmenter(PARAMS, tile_size=4_096)  # force multi-tile path
    for name, data in _corpora(rng).items():
        assert tpu.cuts(data).tolist() == cpu.cuts(data).tolist(), name


def test_tpu_chunks_match_cpu_digests(rng):
    data = rng.integers(0, 256, size=50_000, dtype=np.uint8).tobytes()
    cpu = CpuCdcFragmenter(PARAMS).chunk(data)
    tpu = TpuCdcFragmenter(PARAMS, tile_size=8_192, hash_batch=16).chunk(data)
    assert cpu == tpu


def test_chunk_size_bounds(rng):
    data = rng.integers(0, 256, size=100_000, dtype=np.uint8).tobytes()
    chunks = CpuCdcFragmenter(PARAMS).chunk(data)
    assert sum(c.length for c in chunks) == len(data)
    for c in chunks[:-1]:
        assert PARAMS.min_size <= c.length <= PARAMS.max_size
    assert chunks[-1].length <= PARAMS.max_size


def test_reconstruction_byte_identical(rng):
    data = rng.integers(0, 256, size=30_000, dtype=np.uint8).tobytes()
    chunks = TpuCdcFragmenter(SMALL, tile_size=4_096).chunk(data)
    rebuilt = b"".join(data[c.offset:c.offset + c.length] for c in chunks)
    assert rebuilt == data


def test_dedup_shift_resilience(rng):
    """Content-defined chunking's raison d'être: inserting bytes near the
    front must leave most downstream chunk digests unchanged — the fixed-N
    reference splitter (StorageNode.java:138-155) shares ~0% instead."""
    base = rng.integers(0, 256, size=200_000, dtype=np.uint8).tobytes()
    edited = base[:100] + b"INSERTED!" + base[100:]
    frag = CpuCdcFragmenter(PARAMS)
    d1 = {c.digest for c in frag.chunk(base)}
    d2 = [c.digest for c in frag.chunk(edited)]
    shared = sum(1 for d in d2 if d in d1)
    assert shared / len(d2) > 0.9


def test_forced_cuts_on_zeros():
    """All-zero input has no candidates past the first bytes → every chunk is
    forced at max_size (pathological case from SURVEY.md §7.4)."""
    data = bytes(PARAMS.max_size * 3 + 10)
    cuts = CpuCdcFragmenter(PARAMS).cuts(data).tolist()
    assert cuts == cdc_cuts_ref(data, PARAMS)
    assert all(b - a <= PARAMS.max_size for a, b in zip([0] + cuts, cuts))
