"""Headline benchmark: anchored CDC chunk+hash throughput (GiB/s per chip).

The reference publishes no numbers (BASELINE.md) — the metric and the
north-star target come from BASELINE.json: >5 GiB/s sustained content-defined
chunking + per-chunk SHA-256 on one TPU v5e chip, with byte-identical
reconstruction. ``vs_baseline`` is therefore reported against the 5 GiB/s
north-star target (reference itself: single-threaded Java MessageDigest,
well under 1 GiB/s, but unmeasurable here — no JDK, SURVEY.md preamble).

Measures the **anchored two-level CDC pipeline** (dfs_tpu.ops.cdc_anchored)
— the production flagship: byte-granular content anchors re-sync the chunk
grid after unaligned edits (dedup ratio: bench_dedup.py, latest artifact
DEDUP_r03.json) while chunk+hash runs as the fused device chain
anchor-hash -> segment-select -> lane repack -> windowed-Gear candidates ->
lane-parallel selection -> strip-scan SHA-256 (Pallas, 8 blocks per grid
step) -> on-device compaction with device-side offsets. The chain
dispatches asynchronously end to end (the carry is a device scalar), so a
multi-region stream has no host sync until results are pulled.

Two numbers are reported (the round-1 conflation of compile+staging+compute
is gone):
- stdout JSON (the driver's record): **resident sustained** GiB/s — region
  buffer in HBM, min(difference-of-mins, paired-slope-median) over
  adjacent k=10/k=40 chain-timing pairs spread across ~2.5 minutes of
  the shared chip's contention plateaus (raw samples embedded in the
  JSON). Scope: this is the KERNEL capability. The overlapped ingest
  path (double-buffered device_put, fragmenter/cdc_anchored.py) can in
  principle converge to it when staging outruns the chain (>= ~8 GB/s
  for a 64 MiB/8 ms region), but this harness's tunnel has never
  offered that (measured 10-1500 MB/s), so end-to-end convergence is
  untested — the recorded end-to-end numbers are the CPU engine's
  (E2E artifacts, bench_e2e_stream.py).
- stderr: warm end-to-end (staging + compute, compile excluded) — the
  harness's SHARED device tunnel swings from ~1.5 GB/s to ~10 MB/s hour
  to hour (measured round 3), so this number tracks link contention, not
  the pipeline; recorded for honesty. bench_e2e_stream.py measures the
  end-to-end shape properly, against the CPU engine `auto` falls back to.

Prints exactly ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": "GiB/s", "vs_baseline": N}
Diagnostics go to stderr.
"""

from __future__ import annotations

import hashlib
import json
import statistics
import sys
import time

import numpy as np

NORTH_STAR_GIBPS = 5.0


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_corpus(size: int, seed: int = 0) -> np.ndarray:
    """Synthetic corpus ~ '1 GiB synthetic tarball' config (BASELINE.json
    configs[2]), scaled: random base blocks with repeated sections so dedup
    has something to find."""
    rng = np.random.default_rng(seed)
    block = rng.integers(0, 256, size=4 * 1024 * 1024, dtype=np.uint8)
    reps = int(np.ceil(size / block.size))
    arr = np.tile(block, reps)[:size].copy()
    # splice fresh randomness into half the blocks so it's not pure repeats
    for off in range(0, size, 8 * 1024 * 1024):
        end = min(off + 4 * 1024 * 1024, size)
        arr[off:end] = rng.integers(0, 256, size=end - off, dtype=np.uint8)
    return arr


def main() -> int:
    size = int(sys.argv[1]) if len(sys.argv) > 1 else 256 * 1024 * 1024
    passes = max(2, int(sys.argv[2])) if len(sys.argv) > 2 else 12

    import jax

    from dfs_tpu.fragmenter.cdc_anchored import AnchoredTpuFragmenter
    from dfs_tpu.ops.cdc_anchored import (AnchoredCdcParams, region_buffer,
                                          region_collect, region_dispatch)

    dev = jax.devices()[0]
    log(f"device: {dev} platform={dev.platform}")

    params = AnchoredCdcParams()         # 96..128 KiB segments, 2K/8K/64K
    region = 64 * 1024 * 1024
    size = max(size, region)
    frag = AnchoredTpuFragmenter(params, region_bytes=region)
    data = make_corpus(size)
    log(f"corpus: {size / 2**20:.0f} MiB, regions of {region / 2**20:.0f} MiB"
        f" (stride {frag.stride / 2**20:.2f} MiB, pipelined walk)")

    # ---- correctness gate + warm end-to-end (compile excluded) ----------
    chunks = frag.chunk(data.tobytes())           # compiles everything
    t0 = time.perf_counter()
    chunks = frag.chunk(data.tobytes())
    e2e = time.perf_counter() - t0
    assert sum(c.length for c in chunks) == size, "chunks must tile corpus"
    for c in (chunks[0], chunks[len(chunks) // 2], chunks[-1]):
        # raw hashlib ON PURPOSE: this gate is the independent oracle the
        # production digest path is checked AGAINST — routing it through
        # dfs_tpu.utils.hashing would make the check circular
        # dfslint: ignore[DFS004]
        want = hashlib.sha256(
            data[c.offset:c.offset + c.length].tobytes()).hexdigest()
        assert c.digest == want, "digest mismatch vs hashlib"
    log(f"warm end-to-end chunk() incl. host->device staging: {e2e:.2f}s "
        f"({size / e2e / 2**30:.3f} GiB/s), {len(chunks)} chunks, "
        f"mean {size / len(chunks):.0f} B")

    # ---- sustained resident throughput: multi-pass slope ----------------
    reg = data[:region]
    words = jax.device_put(region_buffer(reg, np.zeros((8,), np.uint8),
                                         params))
    out = region_dispatch(words, region, 0, True, params)
    spans, consumed = region_collect(out)         # warm + sanity
    assert consumed == region and sum(ln for _, ln, _ in spans) == region
    # independent oracle, like the warm-path gate above
    # dfslint: ignore[DFS004]
    want = hashlib.sha256(reg[spans[1][0]:spans[1][0] + spans[1][1]]
                          .tobytes()).hexdigest()
    assert spans[1][2] == want, "resident-path digest mismatch vs hashlib"
    log(f"resident warm: {len(spans)} chunks in one region")

    # Estimator (round-4 revision; raw samples ship in the JSON so the
    # record is auditable). Two amortized chain lengths k_lo < k_hi are
    # timed as ADJACENT PAIRS (order alternating per rep, so neither side
    # systematically samples earlier in a contention plateau), with reps
    # spread over ~2.5 minutes — longer than the tunnel's contention
    # plateaus, which a ~30 s spread fit inside (round-3 record: one calm
    # k_lo catch, zero calm k_hi catches -> difference-of-mins overshot
    # 12.9 ms in a round whose calm regions measured 7-8 ms). Two
    # estimates, each safe against a different failure mode:
    #   * dmin = (min t_hi - min t_lo)/(k_hi - k_lo): exact when both
    #     sides catch a calm window; overshoots when only k_lo does.
    #   * pairmed = median over reps of (t_hi - t_lo)/(k_hi - k_lo):
    #     per-pair slopes share one regime (adjacent in time), so the
    #     median tracks the TYPICAL regime's real cost; single lucky
    #     (biased-low, the round-2 trap) or unlucky pairs cannot move it.
    # Recorded: min(dmin, pairmed) — the calm-window capability when the
    # spread catches it on both sides, else the typical-regime cost;
    # neither component can sit below the pipeline cost of its regime.
    #
    # k choice bounds the third failure mode: the sync round-trip itself
    # jitters ±40 ms on this tunnel, so with k_hi - k_lo = 9 a single
    # low-sync catch on one side moves the estimate by up to ~4 ms/region
    # (observed: one t12=161 ms against a 197-210 cluster -> a bogus
    # 4.1 ms "calm" read). With k_hi - k_lo = 30 the same outlier moves
    # it by at most ~1.3 ms, below the quantity being measured.
    k_lo, k_hi = 10, max(passes, 40)
    reps = 28      # ~3 min spread: a worst-hour driver run still gets
    #                several chances at calm plateaus on BOTH chain sizes
    t_lo, t_hi = [], []
    t_start = time.perf_counter()
    for rep in range(reps):
        if rep:
            time.sleep(5.5)
        order = ((k_lo, t_lo), (k_hi, t_hi))
        if rep % 2:
            order = order[::-1]
        for k, acc in order:
            jax.block_until_ready(
                region_dispatch(words, region, 0, True, params))
            t0 = time.perf_counter()
            for _ in range(k):
                out = region_dispatch(words, region, 0, True, params)
            jax.block_until_ready(out)
            acc.append(time.perf_counter() - t0)
    span = time.perf_counter() - t_start
    dmin = (min(t_hi) - min(t_lo)) / (k_hi - k_lo)
    pairmed = statistics.median(
        (h - l) / (k_hi - k_lo) for l, h in zip(t_lo, t_hi))
    dt = min(dmin, pairmed)
    gibps = region / dt / 2**30
    log(f"sustained resident: {dt * 1e3:.2f} ms/region over a "
        f"{span:.0f} s spread (dmin {dmin * 1e3:.2f} ms from "
        f"min t{k_lo}={min(t_lo) * 1e3:.0f} / "
        f"min t{k_hi}={min(t_hi) * 1e3:.0f} ms; "
        f"paired-slope median {pairmed * 1e3:.2f} ms)")
    log(f"  t{k_lo} ms: {[f'{t * 1e3:.0f}' for t in t_lo]}")
    log(f"  t{k_hi} ms: {[f'{t * 1e3:.0f}' for t in t_hi]}")

    print(json.dumps({
        "metric": "anchored_cdc_chunk_hash_throughput_resident",
        "value": round(gibps, 3),
        "unit": "GiB/s",
        "vs_baseline": round(gibps / NORTH_STAR_GIBPS, 3),
        "samples": {
            "k_lo": k_lo, "k_hi": k_hi, "span_s": round(span, 1),
            "order": "adjacent pairs, alternating per rep",
            "t_lo_s": [round(t, 4) for t in t_lo],
            "t_hi_s": [round(t, 4) for t in t_hi],
            "dmin_ms": round(dmin * 1e3, 3),
            "pair_median_ms": round(pairmed * 1e3, 3),
            "dt_ms": round(dt * 1e3, 3),
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
