"""CPU hashing helpers.

The reference's entire hash engine is ``sha256Hex(byte[])`` via
``java.security.MessageDigest`` returning lowercase hex
(StorageNode.java:603-613). This module is the host-side equivalent; the TPU
batched implementation lives in ``dfs_tpu.ops.sha256_jax`` and is verified
bit-exact against this one. When the optional C++ native library is built
(``dfs_tpu/native``), it accelerates bulk hashing transparently.
"""

from __future__ import annotations

import hashlib

import numpy as np


def sha256_hex(data: bytes | bytearray | memoryview | np.ndarray) -> str:
    """Lowercase-hex SHA-256, the system-wide content address
    (fileId = sha256(file) — StorageNode.java:127)."""
    if isinstance(data, np.ndarray):
        data = data.tobytes()
    return hashlib.sha256(data).hexdigest()


def sha256_new() -> "hashlib._Hash":
    """Fresh incremental SHA-256 hasher (update()/hexdigest()) for
    whole-stream ids hashed block by block. This module is the one place
    outside ``dfs_tpu/ops`` allowed to touch hashlib directly (dfslint
    DFS004): every digest in the system routes through here so the
    content-address namespace cannot be split by a second, differently-
    configured hash implementation."""
    return hashlib.sha256()


def sha256_many_hex(chunks: list[bytes]) -> list[str]:
    """Digest a batch of byte strings via hashlib. Measured: OpenSSL's
    SHA-NI assembly under hashlib runs 1.0 GiB/s vs 0.19 for the portable
    C++ batch in dfs_tpu/native (which exists for non-Python hosts linking
    the library, not as a Python accelerator)."""
    return [hashlib.sha256(c).hexdigest() for c in chunks]


def gear_table(seed: int = 0x9E3779B9) -> np.ndarray:
    """Deterministic 256-entry uint32 Gear table via splitmix64.

    Both the CPU oracle and the TPU kernel index this same table, so chunk
    boundaries are identical across backends by construction.
    """
    out = np.empty(256, dtype=np.uint64)
    x = np.uint64(seed & 0xFFFFFFFFFFFFFFFF)
    GOLDEN = np.uint64(0x9E3779B97F4A7C15)
    M1 = np.uint64(0xBF58476D1CE4E5B9)
    M2 = np.uint64(0x94D049BB133111EB)
    with np.errstate(over="ignore"):
        for i in range(256):
            x = x + GOLDEN
            z = x
            z = (z ^ (z >> np.uint64(30))) * M1
            z = (z ^ (z >> np.uint64(27))) * M2
            z = z ^ (z >> np.uint64(31))
            out[i] = z
    return (out & np.uint64(0xFFFFFFFF)).astype(np.uint32)


_HEX = frozenset("0123456789abcdef")


def is_hex_digest(s: str) -> bool:
    """True iff ``s`` is a 64-char lowercase-hex SHA-256 digest — the only
    legal file/chunk id format (shared by the store and the HTTP layer so
    the 400 gate and the ValueError gate cannot diverge). set() over the
    string keeps the check in C — this gate runs per chunk access and a
    per-character genexpr measured ~0.5 s per 3 GiB-class degraded read."""
    return len(s) == 64 and set(s) <= _HEX


def next_pow2(x: int) -> int:
    """Smallest power of two >= max(1, x)."""
    return 1 << (max(1, x) - 1).bit_length()
