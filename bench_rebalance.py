"""Elastic-membership acceptance bench -> REBALANCE_r14.json: add then
drain a node on a REAL 3->4->3-process cluster under open-loop load
(dfs_tpu/ring, docs/membership.md).

Topology: 4 ``dfs-tpu serve`` processes share the address book
(``--nodes 4``) but the placement ring starts with members 1,2,3 at 64
vnodes (``--ring-members 1,2,3 --ring-vnodes 64``) — node 4 is a
reachable STANDBY. The scenario:

1. **warm** — open-loop multi-tenant Zipf load against nodes 1-3 builds
   an acked catalog (the LoadGen ledger: fileId == sha256(body)).
2. **add**  — ``POST /ring {add, nodeId: 4}`` mid-load bumps the epoch;
   every node's rebalancer streams the displaced digests to node 4
   under the configured byte credits while reads ride the dual-read
   window. The bench reconstructs BOTH epoch maps from ``GET /ring``
   (placement is computable by any party from the compact map — that
   is the point) and computes the THEORETICAL MINIMUM movement over
   the pre-add catalog: sum of len(d) x |newOwners(d) \\ oldOwners(d)|.
3. **drain** — ``POST /ring {drain, nodeId: 4}`` (weight 0) moves
   everything back off; convergence must reach a fully CLEAN census
   (over-replication zero = every stray relocated home) and node 4's
   CAS must be EMPTY.
4. **verify** — every acked upload downloads byte-identical.

Gates (the r14 acceptance criteria):
- zero failed reads across the whole run (dual-read window held);
- zero acked-write loss (every 201 readable after 3->4->3);
- moved bytes <= 1.5x the theoretical minimum + rf x bytes uploaded
  concurrently with the move (those new digests may legitimately move
  or place either side of the flip);
- per-node rebalance bandwidth <= the configured credit (x1.35 for
  token-bucket burst + measurement slack);
- post-drain census fully clean and node 4 CAS empty.

Usage: python bench_rebalance.py [--tiny] [--out PATH]
Writes REBALANCE_r14.json (or --out) and prints it.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))

from dfs_tpu.ring import RingMap  # noqa: E402
from scripts.chaos_harness import (ClusterHarness, HarnessError,  # noqa: E402
                                   LoadGen)

ART = "REBALANCE_r14.json"
N = 4
RF = 2
VNODES = 64
MEMBERS0 = "1,2,3"


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _catalog(h: ClusterHarness, node_id: int = 1) -> dict[str, int]:
    """digest -> byte length over every manifest the node holds
    (announce-to-all: any node's manifest dir is the catalog)."""
    out: dict[str, int] = {}
    status, body = h.http(node_id, "GET", "/files")
    if status != 200:
        raise HarnessError(f"GET /files -> {status}")
    for f in json.loads(body):
        status, mj = h.http(node_id, "GET",
                            f"/manifest?fileId={f['fileId']}")
        if status != 200:
            continue
        for c in json.loads(mj)["chunks"]:
            out.setdefault(c["digest"], c["length"])
    return out


def _ring_map(h: ClusterHarness, node_id: int = 1) -> RingMap:
    st = h.ring_status(node_id)
    return RingMap.from_dict({"epoch": st["epoch"],
                              "vnodes": st["vnodes"],
                              "members": st["members"]})


def _min_movement(catalog: dict[str, int], old: RingMap, new: RingMap,
                  rf: int) -> int:
    """Theoretical minimum bytes a rebalance must move: every byte of
    every copy that exists at a NEW owner but not at an OLD one."""
    total = 0
    for d, ln in catalog.items():
        moved = set(new.owners(d, rf)) - set(old.owners(d, rf))
        total += ln * len(moved)
    return total


def _rebalance_totals(h: ClusterHarness, nodes) -> dict[int, dict]:
    out = {}
    for i in nodes:
        r = h.metrics(i).get("ring", {}).get("rebalance", {})
        out[i] = {"bytesMoved": r.get("bytesMoved", 0),
                  "pushes": r.get("pushes", 0),
                  "creditStallS": r.get("creditStallS", 0.0),
                  "dualReadHits": r.get("dualReadHits", 0)}
    return out


def _migrate(h: ClusterHarness, load: LoadGen, action: dict,
             new_epoch: int, window_s: float, converge_s: float,
             credit: int) -> dict:
    """One membership change under load: snapshot the catalog + maps,
    fire the admin action mid-load, wait for cluster-wide convergence,
    and judge moved bytes against the theoretical minimum."""
    nodes = list(range(1, h.n + 1))
    pre_catalog = _catalog(h)
    pre_ring = _ring_map(h)
    pre_tot = _rebalance_totals(h, nodes)
    pre_reads = load.snapshot()
    t_load = threading.Thread(target=load.run_for, args=(window_s,),
                              daemon=True)
    t_load.start()
    time.sleep(max(0.3, window_s / 6))   # change lands mid-load
    t0 = time.time()
    out = h.ring_post(1, **action)
    assert out["epoch"] == new_epoch, out
    new_ring = RingMap.from_dict(out["ring"])
    h.wait_ring_converged(new_epoch, nodes, timeout=converge_s)
    seconds = time.time() - t0
    t_load.join()
    load.drain()

    post_catalog = _catalog(h)
    post_tot = _rebalance_totals(h, nodes)
    per_node = {
        str(i): {k: (post_tot[i][k] - pre_tot[i][k]
                     if isinstance(post_tot[i][k], (int, float))
                     else post_tot[i][k])
                 for k in post_tot[i]}
        for i in nodes}
    moved = sum(v["bytesMoved"] for v in per_node.values())
    min_pre = _min_movement(pre_catalog, pre_ring, new_ring, h.rf)
    new_bytes = sum(ln for d, ln in post_catalog.items()
                    if d not in pre_catalog)
    bound = 1.5 * min_pre + h.rf * new_bytes
    # bandwidth: a node's long-run rebalance rate is credit-bounded
    # (one-slice token-bucket overshoot + wall-clock slack -> x1.35);
    # nodes that moved less than one credit-second cannot violate it
    bw_ok = all(
        v["bytesMoved"] <= credit * 1.0 or
        v["bytesMoved"] / max(seconds, 1e-6) <= credit * 1.35
        for v in per_node.values())
    snap = load.snapshot()
    reads_failed = (snap["downloads_failed"] + snap["download_mismatch"]
                    - pre_reads["downloads_failed"]
                    - pre_reads["download_mismatch"])
    return {
        "epoch": new_epoch,
        "seconds": round(seconds, 2),
        "moved_bytes": moved,
        "min_bytes": min_pre,
        "concurrent_new_bytes": new_bytes,
        "moved_bound": round(bound),
        "moved_within_bound": moved <= bound and min_pre > 0,
        "bandwidth_ok": bw_ok,
        "credit_stall_s": round(sum(v["creditStallS"]
                                    for v in per_node.values()), 3),
        "dual_read_hits": sum(v["dualReadHits"]
                              for v in per_node.values()),
        "reads_failed_during": reads_failed,
        "per_node": per_node,
        "catalog_digests": len(post_catalog),
    }


def run(tmp: Path, tiny: bool) -> dict:
    credit = 512 * 1024 if tiny else 2 * 1024 * 1024
    p = {"payload": 48_000 if tiny else 192_000,
         "rate": 4.0 if tiny else 6.0,
         "warm_s": 4.0 if tiny else 10.0,
         "window_s": 3.0 if tiny else 8.0,
         "converge_s": 60.0 if tiny else 120.0,
         "op_timeout": 60.0 if tiny else 120.0}
    out: dict = {"metric": "rebalance_invariants", "round": 14,
                 "workload": {"nodes": N, "rf": RF, "vnodes": VNODES,
                              "members0": MEMBERS0,
                              "credit_bytes_per_s": credit,
                              "tiny": tiny, **p}}
    h = ClusterHarness(
        N, tmp, rf=RF, repair_interval_s=1.0, chaos=False,
        extra_flags=["--ring-vnodes", str(VNODES),
                     "--ring-members", MEMBERS0,
                     "--ring-rebalance-credit-bytes", str(credit)])
    try:
        t0 = time.time()
        h.start_all()
        h.wait_ready()
        out["workload"]["startup_s"] = round(time.time() - t0, 1)
        load = LoadGen(h, p["payload"], rate_per_s=p["rate"], seed=1414,
                       upload_nodes=[1, 2, 3], download_nodes=[1, 2, 3],
                       upload_fraction=0.6,
                       op_timeout_s=p["op_timeout"])
        load.run_for(p["warm_s"])          # seed the acked catalog
        log(f"warm done: {load.snapshot()['acked']} acked")

        # during migrations the open-loop mix turns read-heavy: the
        # reads are what the dual-read gate exercises, and a lighter
        # upload stream keeps the moved-vs-minimum comparison tight
        load.upload_fraction = 0.25
        out["add"] = _migrate(
            h, load, {"action": "add", "nodeId": 4}, 1,
            p["window_s"], p["converge_s"], credit)
        log(f"add: {json.dumps(out['add']['moved_bytes'])}B moved "
            f"(min {out['add']['min_bytes']}B) in "
            f"{out['add']['seconds']}s")

        out["drain"] = _migrate(
            h, load, {"action": "drain", "nodeId": 4}, 2,
            p["window_s"], p["converge_s"], credit)
        log(f"drain: {out['drain']['moved_bytes']}B moved "
            f"(min {out['drain']['min_bytes']}B) in "
            f"{out['drain']['seconds']}s")

        # post-drain: census fully clean (over-replication zero = every
        # stray relocated home) and node 4 holds no chunk bytes
        rep = h.wait_census_clean(1, timeout=p["converge_s"])
        cap4 = ((rep.get("capacity") or {}).get("nodes")
                or {}).get("4") or {}
        out["census"] = {
            "under_replicated": rep.get("underReplicatedTotal", -1),
            "over_replicated": rep.get("overReplicatedTotal", -1),
            "orphaned": rep.get("orphanedTotal", -1),
            "in_flight": rep.get("inFlightTotal", -1),
            "peers_failed": rep.get("peersFailed", -1),
            "node4_cas_chunks": cap4.get("casChunks", -1)}
        out["census"]["clean"] = (
            out["census"]["under_replicated"] == 0
            and out["census"]["over_replicated"] == 0
            and out["census"]["orphaned"] == 0
            and out["census"]["peers_failed"] == 0
            and out["census"]["node4_cas_chunks"] == 0)

        snap = load.snapshot()
        out["reads_failed"] = (snap["downloads_failed"]
                               + snap["download_mismatch"])
        out["zero_failed_reads"] = out["reads_failed"] == 0
        verify = load.verify_all(nodes=[1, 2, 3])
        out["acked"] = snap["acked"]
        out["uploads_failed"] = snap["uploads_failed"]
        out["verified"] = verify["ok"]
        out["lost"] = verify["lost"]
        out["zero_acked_loss"] = not verify["lost"]
        out["ok"] = bool(
            out["zero_failed_reads"] and out["zero_acked_loss"]
            and out["add"]["moved_within_bound"]
            and out["add"]["bandwidth_ok"]
            and out["drain"]["moved_within_bound"]
            and out["drain"]["bandwidth_ok"]
            and out["census"]["clean"] and out["acked"] > 0)
    finally:
        h.stop_all()
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tiny", action="store_true",
                    help="tier-1 smoke mode: small payloads, short "
                         "windows — same scenario, same gates")
    ap.add_argument("--out", default=None,
                    help=f"artifact path (default: {ART} next to this "
                         "script)")
    args = ap.parse_args(argv)
    out_path = Path(args.out) if args.out \
        else Path(__file__).parent / ART
    with tempfile.TemporaryDirectory(prefix="bench_rebalance_") as tmp:
        out = run(Path(tmp), args.tiny)
    out_path.write_text(json.dumps(out, indent=2) + "\n")
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
