"""gRPC sidecar: the accelerator pipeline as a local service (north star,
BASELINE.json: "The Java StorageNode calls the TPU backend over a local gRPC
sidecar during upload").

Any host process — a storage node written in another language, or a Python
node that wants the TPU in a separate process so device init/compile never
blocks the serving loop — streams bytes in and gets chunk boundaries +
per-chunk SHA-256 digests back.

The wire contract uses gRPC *generic* handlers with identity (bytes)
serialization: the environment ships grpcio but not grpc_tools/protoc-gen-py,
and the payloads are length-delimited binary anyway (protobuf would Base64
nothing, buy nothing). Methods (all under service ``dfs.Sidecar``):

- ``ChunkHash``  unary-unary. Request: raw file bytes. Response: JSON header
  (chunk table: offset/length/digest + params echo) — the exact information
  the node runtime needs to build a Manifest.
- ``Health``     unary-unary. Request: empty. Response: JSON status.

The sidecar accepts a ``fragmenter`` name at startup ("cdc" CPU NumPy or
"cdc-tpu" JAX/TPU) — the node runtime's plugin choice, reference §2.3 analog.
"""

from __future__ import annotations

import json
from concurrent import futures

import grpc

_SERVICE = "dfs.Sidecar"


def _identity(x: bytes) -> bytes:
    return x


class SidecarServer:
    def __init__(self, port: int = 0, fragmenter: str = "cdc",
                 cdc_params=None, max_workers: int = 4) -> None:
        from dfs_tpu.fragmenter.base import get_fragmenter

        self.fragmenter = get_fragmenter(fragmenter, cdc_params=cdc_params)
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=[("grpc.max_receive_message_length", 1 << 30),
                     ("grpc.max_send_message_length", 1 << 30)])
        self._server.add_generic_rpc_handlers((self._handlers(),))
        self.port = self._server.add_insecure_port(f"127.0.0.1:{port}")

    def _handlers(self) -> grpc.GenericRpcHandler:
        def chunk_hash(request: bytes, ctx) -> bytes:
            chunks = self.fragmenter.chunk(request)
            return json.dumps({
                "fragmenter": self.fragmenter.name,
                "size": len(request),
                "chunks": [{"index": c.index, "offset": c.offset,
                            "length": c.length, "digest": c.digest}
                           for c in chunks],
            }).encode()

        def health(request: bytes, ctx) -> bytes:
            return json.dumps({"ok": True,
                               "fragmenter": self.fragmenter.name}).encode()

        methods = {
            f"/{_SERVICE}/ChunkHash": grpc.unary_unary_rpc_method_handler(
                chunk_hash, request_deserializer=_identity,
                response_serializer=_identity),
            f"/{_SERVICE}/Health": grpc.unary_unary_rpc_method_handler(
                health, request_deserializer=_identity,
                response_serializer=_identity),
        }

        class Handler(grpc.GenericRpcHandler):
            def service(self, call_details):
                return methods.get(call_details.method)

        return Handler()

    def start(self) -> None:
        self._server.start()

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace)


class SidecarClient:
    def __init__(self, port: int, host: str = "127.0.0.1") -> None:
        self._channel = grpc.insecure_channel(
            f"{host}:{port}",
            options=[("grpc.max_receive_message_length", 1 << 30),
                     ("grpc.max_send_message_length", 1 << 30)])
        self._chunk_hash = self._channel.unary_unary(
            f"/{_SERVICE}/ChunkHash", request_serializer=_identity,
            response_deserializer=_identity)
        self._health = self._channel.unary_unary(
            f"/{_SERVICE}/Health", request_serializer=_identity,
            response_deserializer=_identity)

    def chunk_hash(self, data: bytes) -> dict:
        return json.loads(self._chunk_hash(data))

    def health(self) -> dict:
        return json.loads(self._health(b""))

    def close(self) -> None:
        self._channel.close()
