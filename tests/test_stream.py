"""Streaming CDC: incremental chunking over block streams must produce
exactly the same manifests as one-shot chunking, with bounded state —
plus the node-level streaming-ingest contracts (windowed placement
equivalence, the abort path of a failed placement)."""

import asyncio

import numpy as np
import pytest

from dfs_tpu.config import (CDCParams, ClusterConfig, IngestConfig,
                            NodeConfig)
from dfs_tpu.fragmenter.cdc_cpu import CpuCdcFragmenter
from dfs_tpu.fragmenter.cdc_tpu import TpuCdcFragmenter
from dfs_tpu.fragmenter.fixed import FixedFragmenter
from dfs_tpu.fragmenter.stream import StreamChunker, reblock
from dfs_tpu.utils.hashing import sha256_hex

PARAMS = CDCParams(min_size=64, avg_size=256, max_size=1024)


def _blocks(data: bytes, sizes):
    out, off = [], 0
    i = 0
    while off < len(data):
        s = sizes[i % len(sizes)]
        out.append(data[off:off + s])
        off += s
        i += 1
    return out


def test_stream_chunker_matches_oneshot(rng):
    frag = CpuCdcFragmenter(PARAMS)
    data = rng.integers(0, 256, size=100_000, dtype=np.uint8).tobytes()
    for sizes in ([1000], [1], [4096, 33, 777], [100_000]):
        if sizes == [1]:  # 1-byte feeds are slow; shrink the input
            payload = data[:3000]
        else:
            payload = data
        chunker = StreamChunker(PARAMS, frag.bitmap_tile)
        spans = []
        for b in _blocks(payload, sizes):
            spans.extend(chunker.feed(b))
        spans.extend(chunker.finish())
        want = [(c.offset, payload[c.offset:c.offset + c.length])
                for c in frag.chunk(payload)]
        assert [(o, p) for o, p in spans] == want, f"sizes={sizes}"


def test_cpu_manifest_stream_matches(rng, tmp_path):
    frag = CpuCdcFragmenter(PARAMS)
    data = rng.integers(0, 256, size=80_000, dtype=np.uint8).tobytes()
    stored = {}
    m = frag.manifest_stream(_blocks(data, [7000, 123]), "s.bin",
                             store=lambda d, b: stored.__setitem__(d, b))
    assert m == frag.manifest(data, "s.bin")
    assert m.file_id == sha256_hex(data)
    rebuilt = b"".join(stored[c.digest] for c in m.chunks)
    assert rebuilt == data


def test_tpu_manifest_stream_matches(rng):
    cpu = CpuCdcFragmenter(PARAMS)
    tpu = TpuCdcFragmenter(PARAMS, tile_size=8_192, hash_batch=16)
    data = rng.integers(0, 256, size=60_000, dtype=np.uint8).tobytes()
    m = tpu.manifest_stream(_blocks(data, [10_000, 321]), "t.bin")
    want = cpu.manifest(data, "t.bin")
    assert m.fragmenter == "cdc-tpu"  # only the label differs
    assert (m.file_id, m.size, m.chunks) == (want.file_id, want.size,
                                             want.chunks)


def test_fixed_manifest_stream_fallback(rng):
    frag = FixedFragmenter(parts=5)
    data = rng.integers(0, 256, size=1_000, dtype=np.uint8).tobytes()
    m = frag.manifest_stream(_blocks(data, [100]), "f.bin")
    assert m == frag.manifest(data, "f.bin")


def test_chunk_falls_back_to_streaming_beyond_offset_range(rng):
    """Streams past the int32 device-offset ceiling must route through the
    streaming path (offset-free) and still match the CPU oracle. The ceiling
    is shrunk here to keep the test small."""
    tpu = TpuCdcFragmenter(PARAMS, tile_size=4_096, hash_batch=16)
    tpu._max_resident = 20_000
    data = rng.integers(0, 256, size=50_000, dtype=np.uint8).tobytes()
    got = tpu.chunk(data)
    want = CpuCdcFragmenter(PARAMS).chunk(data)
    assert got == want


def test_reblock_exact_tiles(rng):
    data = rng.integers(0, 256, size=10_000, dtype=np.uint8).tobytes()
    tiles = list(reblock(_blocks(data, [999]), 4096))
    assert [t.shape[0] for t in tiles] == [4096, 4096, 1808]
    assert b"".join(t.tobytes() for t in tiles) == data


def test_bounded_state(rng):
    """Resident buffer must never exceed max_size + feed block."""
    frag = CpuCdcFragmenter(PARAMS)
    chunker = StreamChunker(PARAMS, frag.bitmap_tile)
    data = rng.integers(0, 256, size=200_000, dtype=np.uint8).tobytes()
    worst = 0
    for b in _blocks(data, [4096]):
        for _ in chunker.feed(b):
            pass
        worst = max(worst, len(chunker.buf))
    assert worst <= PARAMS.max_size + 4096


# ---------------------------------------------------------------------- #
# node-level streaming ingest (upload_stream): windowed placement
# equivalence and the placement-failure abort path. A 1-node cluster
# needs no listeners — upload_stream only touches the local store.
# ---------------------------------------------------------------------- #

def _stream_node(tmp_path, sub: str, window: int = 2,
                 flush: int = 64 * 1024):
    from dfs_tpu.node.runtime import StorageNodeServer

    cfg = NodeConfig(
        node_id=1, cluster=ClusterConfig.localhost(1, replication_factor=1),
        data_root=tmp_path / sub, fragmenter="cdc", cdc=PARAMS,
        health_probe_s=0, ingest=IngestConfig(window=window))
    node = StorageNodeServer(cfg)
    node._STREAM_FLUSH_BYTES = flush   # several batches on small inputs
    return node


def test_upload_stream_windowed_matches_serial(tmp_path, rng):
    """window=3 must commit the same manifest, stats, and bytes as the
    strictly-serial window=1 schedule (pipelining is a schedule change,
    not a semantics change)."""
    data = rng.integers(0, 256, size=500_000, dtype=np.uint8).tobytes()

    async def upload(window: int):
        node = _stream_node(tmp_path, f"w{window}", window=window)

        async def blocks():
            for off in range(0, len(data), 10_000):
                yield data[off:off + 10_000]

        manifest, stats = await node.upload_stream(blocks(), "s.bin")
        _, gen = await node.download_stream(manifest.file_id)
        got = b"".join([p async for p in gen])
        return manifest, stats, got

    m1, s1, got1 = asyncio.run(upload(1))
    m3, s3, got3 = asyncio.run(upload(3))
    assert (m1.file_id, m1.size, m1.chunks) == (m3.file_id, m3.size,
                                                m3.chunks)
    assert got1 == got3 == data
    assert s1 == s3            # per-batch stats merged deterministically


def test_upload_stream_abort_stops_body_and_commits_nothing(tmp_path, rng):
    """Placement failure mid-stream must abort: stop consuming the body
    (an endless client cannot be drained into memory), commit NO
    manifest, and leave the already-placed chunks as orphans that only
    the AGED GC reclaims (a young orphan may belong to an in-flight
    upload)."""
    from dfs_tpu.node.runtime import StorageNodeServer, UploadError

    node = _stream_node(tmp_path, "abort", window=2, flush=32 * 1024)
    real_place = node._place_batch
    calls = {"n": 0}

    async def flaky_place(file_id, batch, stats, rf=None,
                          placement=None, ledger=None):
        calls["n"] += 1
        if calls["n"] >= 2:
            raise UploadError("Replication failed: injected")
        await real_place(file_id, batch, stats, rf=rf,
                         placement=placement, ledger=ledger)

    node._place_batch = flaky_place
    consumed = {"blocks": 0}
    cap = 50_000                      # hard stop if the abort never fires

    async def endless_body():
        block = rng.integers(0, 256, size=16_384, dtype=np.uint8)
        for i in range(cap):
            consumed["blocks"] += 1
            # fresh content per block (vectorized xor) so CDC keeps
            # producing NEW chunks instead of deduping forever
            yield (block ^ (i & 0xFF)).tobytes()
            await asyncio.sleep(0)

    async def run():
        with pytest.raises(UploadError, match="injected"):
            await node.upload_stream(endless_body(), "doomed.bin")

    asyncio.run(run())
    assert consumed["blocks"] < cap        # reading STOPPED mid-body
    assert node.store.manifests.ids() == []   # no manifest committed
    # an aborted batch's already-submitted CAS-pool job cannot be
    # recalled mid-write — a few orphan puts may land moments after the
    # abort returns; wait for the store to go quiet before snapshotting
    import time as _time
    orphans: list = []
    for _ in range(100):
        cur = sorted(node.store.chunks.digests())
        if cur and cur == orphans:
            break
        orphans = cur
        _time.sleep(0.05)
    assert orphans                         # batch 1 placed, then aborted
    # the aged sweep spares them (could be an in-flight upload's chunks)…
    assert node.store.gc(min_age_s=3600.0) == []
    assert sorted(node.store.chunks.digests()) == orphans
    # …and the explicit sweep reclaims them once aged (age 0 here)
    assert sorted(node.store.gc()) == orphans
    assert node.store.chunks.digests() == []
