"""Flight recorder: a crash-safe, size-bounded on-disk event journal.

The span ring (dfs_tpu/obs) answers "what happened inside this request";
it cannot answer "what went wrong on this node last Tuesday" — lifecycle
events (peer death, admission sheds, RPC retry storms, repair/GC
decisions, loop-lag incidents) vanish with the process, and the ring
evicts under churn. The journal is the durable complement: every
lifecycle event is one JSON line in an append-only segment file, stamped
with the wall clock and the active trace id, so a post-mortem can walk
from "node 3 shed downloads at 14:02" to the exact traces involved.

Design constraints, in order:

- **The event loop never touches disk.** ``emit()`` is a lock-free
  ``queue.Queue.put_nowait`` (dfslint DFS001-clean by construction); a
  dedicated writer thread drains the queue and appends. A full queue
  DROPS the event and counts it (``stats()["dropped"]``) — diagnosis
  must never become backpressure on the system being diagnosed. Disk
  trouble (ENOSPC, a vanished directory) never kills the writer thread
  either: failed writes/rotations are counted (``stats()["ioErrors"]``),
  the batch drops, and journaling resumes when the disk recovers.
- **Crash-safe, not fsync-durable.** Records are newline-terminated
  JSON appended to the active segment; a ``kill -9`` mid-write leaves at
  most one torn final line, which readers silently discard (counted in
  ``stats()["torn"]`` per read). Every boot starts a FRESH segment, so
  a torn tail from the previous life never mixes with live appends.
- **Size-bounded.** The active segment rotates at
  ``segment_bytes``; oldest segments are deleted until the directory
  fits ``total_bytes``. A runaway event source costs history, never
  disk.

Segment names are ``events-<boot unix ts>-<seq>.jsonl`` — sortable
lexically within a boot and chronologically across boots (zero-padded
seq). Segments are opened create-only: a restart within the same
wall-clock second shares the boot timestamp but continues the seq past
the previous life's segments, so an existing file — torn tail included
— is never appended to.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from pathlib import Path

# record shape: {"ts": wall seconds, "type": str, "node": int,
#                "trace": 32-hex or absent, ...event fields}

_SENTINEL = None   # queue item that tells the writer thread to exit


def read_events(root: Path, since: float = 0.0,
                limit: int = 256) -> tuple[list[dict], int]:
    """-> (events with ts >= since, oldest first, at most ``limit``
    NEWEST such events; count of torn/unparsable lines skipped).

    Reads newest segment backwards so a large journal costs ~one
    segment of parsing for the common "recent events" query. Torn final
    records (crash mid-append) and any corrupt line are skipped, never
    fatal — a journal must be readable exactly when the process died
    badly. Segments may vanish mid-read (the writer's budget sweep);
    that is treated as end-of-history, not an error."""
    root = Path(root)
    try:
        segments = sorted(p for p in root.iterdir()
                          if p.name.startswith("events-")
                          and p.name.endswith(".jsonl"))
    # any sick-directory errno (missing, NotADirectory, EACCES…) is
    # empty history, not a 500 — /events must answer exactly when the
    # disk is the thing going wrong
    except OSError:
        return [], 0
    out: list[dict] = []
    torn = 0
    for seg in reversed(segments):
        try:
            raw = seg.read_bytes()
        except OSError:
            continue   # rotated away (or unreadable) under the reader
        batch: list[dict] = []
        complete = raw.endswith(b"\n")
        lines = raw.split(b"\n")
        if lines and lines[-1] == b"":
            lines.pop()
        for i, line in enumerate(lines):
            if not complete and i == len(lines) - 1:
                torn += 1          # torn final record: discard, don't parse
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                torn += 1
                continue
            if isinstance(ev, dict) and ev.get("ts", 0.0) >= since:
                batch.append(ev)
        out = batch + out
        if len(out) >= limit:
            break
    return out[-limit:], torn


class Journal:
    """One node's flight recorder. Construct with the journal directory
    (created if absent); ``emit()`` from any thread; ``close()`` flushes
    and joins the writer."""

    _QUEUE_MAX = 4096

    def __init__(self, root: Path, node_id: int,
                 total_bytes: int = 16 * 1024 * 1024,
                 segment_bytes: int = 2 * 1024 * 1024) -> None:
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.node_id = node_id
        self.total_bytes = max(1, int(total_bytes))
        # a segment larger than the whole budget would let the ACTIVE
        # segment — which the sweep never deletes — overshoot the cap
        # all by itself; the budget wins ("costs history, never disk")
        self.segment_bytes = min(max(1, int(segment_bytes)),
                                 self.total_bytes)
        self._boot = time.time()
        self._seq = 0
        self._q: queue.Queue = queue.Queue(maxsize=self._QUEUE_MAX)
        self._dropped = 0
        self._emitted = 0
        self._io_errors = 0
        self._handled = 0   # records the writer has fully dealt with
        self._lock = threading.Lock()   # counters only
        self._f = None                  # writer-thread-owned
        self._f_bytes = 0
        self._writer = threading.Thread(target=self._run,
                                        name=f"journal-{node_id}",
                                        daemon=True)
        self._writer.start()

    # ---- producer side (any thread, never blocks) --------------------- #

    def emit(self, etype: str, fields: dict | None = None,
             trace: str | None = None) -> None:
        rec = {"ts": time.time(), "type": etype, "node": self.node_id}
        if trace is not None:
            rec["trace"] = trace
        if fields:
            rec.update(fields)
        try:
            self._q.put_nowait(rec)
            with self._lock:
                self._emitted += 1
        except queue.Full:
            with self._lock:
                self._dropped += 1

    # ---- writer thread ------------------------------------------------ #

    def _segment_path(self) -> Path:
        return self.root / f"events-{self._boot:.0f}-{self._seq:06d}.jsonl"

    def _open_segment(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
        # Create-only ("xb"), never append: a restart within the same
        # wall-clock second gets the same <boot ts>, and reopening the
        # previous life's segment in "ab" would glue this boot's first
        # record onto its torn final line — destroying both. Bump seq
        # past whatever names that life claimed instead.
        while True:
            self._seq += 1
            try:
                self._f = open(self._segment_path(), "xb")
                break
            except FileExistsError:
                continue
            except OSError:
                # ENOSPC, EACCES, the journal dir yanked out from under
                # us: the writer thread must SURVIVE — a dead writer
                # silently disables the flight recorder while stats()
                # keeps saying enabled. Count it, leave _f None, and
                # let _write retry a fresh open on the next batch.
                with self._lock:
                    self._io_errors += 1
                return
        self._f_bytes = 0
        self._enforce_budget()

    def _enforce_budget(self) -> None:
        """Delete oldest segments until the directory fits the budget
        (the active segment is never deleted)."""
        active = self._segment_path().name
        try:
            segs = sorted((p for p in self.root.iterdir()
                           if p.name.startswith("events-")
                           and p.name.endswith(".jsonl")
                           and p.name != active),
                          reverse=True)   # newest first
        except OSError:
            return
        budget = self.total_bytes - self._f_bytes
        for p in segs:
            try:
                n = p.stat().st_size
            except OSError:
                continue
            if budget - n < 0:
                try:
                    p.unlink()
                except OSError:
                    pass
            else:
                budget -= n

    def _run(self) -> None:
        self._open_segment()
        while True:
            try:
                rec = self._q.get(timeout=0.25)
            except queue.Empty:
                continue
            if rec is _SENTINEL:
                break
            # drain greedily: one write+flush per wakeup, not per record
            batch = [rec]
            while True:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is _SENTINEL:
                    self._write(batch)
                    if self._f is not None:
                        self._f.close()
                        self._f = None
                    return
                batch.append(nxt)
            self._write(batch)
            with self._lock:
                self._handled += len(batch)
        if self._f is not None:
            self._f.close()
            self._f = None

    def _write(self, batch: list[dict]) -> None:
        lines = []
        for rec in batch:
            try:
                lines.append(json.dumps(rec, separators=(",", ":"))
                             .encode() + b"\n")
            except (TypeError, ValueError):
                continue   # unserializable event field: drop the record
        # rotation is RECORD-granular: a burst bigger than a segment is
        # split at segment boundaries (overshoot bounded by one record),
        # otherwise one giant batch would land in one oversize segment
        # that the budget sweep then deletes wholesale — losing exactly
        # the burst worth keeping. One write+flush per segment chunk.
        i = 0
        while i < len(lines):
            if self._f is None:
                # an earlier rotation/write failed: retry the open so a
                # recovered disk resumes journaling (fresh segment)
                self._open_segment()
                if self._f is None:
                    return   # still broken: drop the rest, counted above
            room = self.segment_bytes - self._f_bytes
            chunk, size = [], 0
            while i < len(lines) and (not chunk or size < room):
                chunk.append(lines[i])
                size += len(lines[i])
                i += 1
            data = b"".join(chunk)
            try:
                self._f.write(data)
                self._f.flush()
            except OSError:
                # disk trouble must not take the node down (and must not
                # take the WRITER down either): count it, ditch the
                # handle so the next batch reopens, drop this batch
                with self._lock:
                    self._io_errors += 1
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None
                return
            self._f_bytes += len(data)
            if self._f_bytes >= self.segment_bytes:
                self._open_segment()

    # ---- read side (blocking file I/O — call via asyncio.to_thread) -- #

    def tail(self, since: float = 0.0, limit: int = 256) -> dict:
        """Recent events (oldest first) + read/write health counters —
        the ``GET /events`` payload."""
        events, torn = read_events(self.root, since=since, limit=limit)
        st = self.stats()
        return {"events": events, "torn": torn,
                "dropped": st["dropped"], "emitted": st["emitted"]}

    def stats(self) -> dict:
        with self._lock:
            emitted, dropped = self._emitted, self._dropped
            io_errors = self._io_errors
        return {"enabled": True, "bytes": self.total_bytes,
                "segmentBytes": self.segment_bytes,
                "emitted": emitted, "dropped": dropped,
                "ioErrors": io_errors}

    def flush(self, timeout_s: float = 5.0) -> None:
        """Block until every event emitted BEFORE this call is on disk
        (tests and shutdown; NOT for the event loop). Queue-empty is not
        enough — the writer drains the queue into a local batch before
        touching the file, so this waits on the written-record count."""
        with self._lock:
            target = self._emitted
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._handled >= target:
                    return
            time.sleep(0.005)

    def close(self) -> None:
        if not self._writer.is_alive():
            return
        try:
            self._q.put(_SENTINEL, timeout=1.0)
        except queue.Full:
            pass
        self._writer.join(timeout=5.0)


__all__ = ["Journal", "read_events"]
