from dfs_tpu.store.cas import ChunkStore, ManifestStore, NodeStore  # noqa: F401
