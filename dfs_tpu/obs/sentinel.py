"""Runtime stall sentinels: the dynamic complement to dfslint DFS001.

The static analyzer proves no *known* blocking idiom sits on the event
loop; it cannot see a new syscall pattern, a pathological GC pause, or a
saturated CAS pool. The sentinel measures the symptoms at runtime: a
periodic sampler that

- measures **event-loop lag** (scheduled wake vs actual wake of an
  ``asyncio.sleep`` — anything occupying the loop shows up here),
- reads the **CAS-pool backlog** (jobs submitted but not yet started —
  the disk tier is saturated),
- tracks **ingest credit stalls** (delta of the ``creditS`` stopwatch —
  chunking blocked on unconsumed output),

and journals an incident (``loop_lag`` / ``cas_backlog`` /
``credit_stall``) when a sample crosses its threshold, trace-free but
timestamped — so "the node went unresponsive around 14:02" is one
``events`` query, not a forensic reconstruction. Last/max gauges are
surfaced under ``/metrics`` ``obs.sentinel`` and in the cluster
doctor's per-node snapshot.

Costs one timer wakeup per ``ObsConfig.sentinel_interval_s`` (default
1 s) and a few dict reads per sample — OBS2_r11.json measures the
everything-on overhead ≤2% on the cached hot-read path.
"""

from __future__ import annotations

import asyncio
import collections
import threading
import time

from dfs_tpu.utils.aio import create_logged_task
from dfs_tpu.utils.logging import get_logger


class Sentinel:
    """One node's sampler. ``start()`` on a running loop; ``stop()`` on
    shutdown. ``cas`` (AsyncChunkStore) and ``stalls`` (the ingest
    Stopwatches) are optional — standalone use samples loop lag only."""

    # CAS jobs pending beyond workers x this factor = a backlog incident
    _CAS_BACKLOG_FACTOR = 4
    # fraction of the sample interval spent credit-stalled that counts
    # as an incident (0.5 = chunking blocked half the interval)
    _CREDIT_STALL_FRACTION = 0.5
    # recency window for the windowed gauges (recentMaxLagS): the
    # doctor's loop_lag rule reads these so one historical spike cannot
    # latch the diagnosis red for the rest of the process lifetime
    RECENT_WINDOW_S = 60.0

    def __init__(self, obs, cas=None, stalls=None,
                 interval_s: float = 1.0, lag_s: float = 0.25) -> None:
        self.obs = obs
        self.cas = cas
        self.stalls = stalls
        self.interval_s = float(interval_s)
        self.lag_s = float(lag_s)
        self.log = get_logger("sentinel", obs.node_id)
        self._task: asyncio.Task | None = None
        self._lock = threading.Lock()
        self._samples = 0
        self._incidents = 0
        self._last_lag = 0.0
        self._max_lag = 0.0
        # (monotonic ts, lag) samples inside RECENT_WINDOW_S, pruned on
        # write AND filtered on read — bounded by window/interval
        self._recent: collections.deque[tuple[float, float]] = \
            collections.deque()
        self._cas_pending = 0
        self._credit_s_prev: float | None = None
        self._credit_stall_last = 0.0

    async def _sample_once(self, lag: float) -> None:
        incidents = 0
        if lag >= self.lag_s:
            incidents += 1
            self.obs.event("loop_lag", lagS=round(lag, 6))
            self.log.warning("event-loop lag %.3fs (threshold %.3fs)",
                             lag, self.lag_s)
        pending = 0
        if self.cas is not None:
            pending = self.cas.pending
            workers = getattr(self.cas, "_workers", 1)
            if pending > workers * self._CAS_BACKLOG_FACTOR:
                incidents += 1
                self.obs.event("cas_backlog", pending=pending,
                               workers=workers)
        credit_delta = 0.0
        if self.stalls is not None:
            credit_s = self.stalls.snapshot().get("creditS", 0.0)
            if self._credit_s_prev is not None:
                credit_delta = max(0.0, credit_s - self._credit_s_prev)
                # duty cycle over the ACTUAL sample period: loop lag
                # stretches the period past interval_s, and judging the
                # stretched delta against the nominal interval would
                # over-fire credit_stall exactly when the loop itself
                # is the pathology
                if credit_delta >= (self.interval_s + lag) \
                        * self._CREDIT_STALL_FRACTION:
                    incidents += 1
                    self.obs.event("credit_stall",
                                   stalledS=round(credit_delta, 6))
            self._credit_s_prev = credit_s
        now = time.monotonic()
        with self._lock:
            self._samples += 1
            self._incidents += incidents
            self._last_lag = lag
            self._max_lag = max(self._max_lag, lag)
            self._recent.append((now, lag))
            cutoff = now - self.RECENT_WINDOW_S
            while self._recent and self._recent[0][0] < cutoff:
                self._recent.popleft()
            self._cas_pending = pending
            self._credit_stall_last = credit_delta

    async def _loop(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            t0 = loop.time()
            await asyncio.sleep(self.interval_s)
            # anything that occupied the loop during the sleep delays
            # the wakeup past the scheduled deadline — that delay IS
            # the loop lag user requests experienced
            lag = loop.time() - t0 - self.interval_s
            await self._sample_once(max(0.0, lag))

    def start(self) -> None:
        if self.interval_s <= 0 or self._task is not None:
            return
        self._task = create_logged_task(self._loop(), self.log, "sentinel")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    def stats(self) -> dict:
        """``/metrics`` ``obs.sentinel`` section + doctor snapshot
        material. ``intervalS`` / ``lagThresholdS`` mirror the ObsConfig
        fields (DFS005)."""
        cutoff = time.monotonic() - self.RECENT_WINDOW_S
        with self._lock:
            recent_max = max((lag for t, lag in self._recent
                              if t >= cutoff), default=0.0)
            return {"enabled": True,
                    "intervalS": self.interval_s,
                    "lagThresholdS": self.lag_s,
                    "samples": self._samples,
                    "incidents": self._incidents,
                    "lastLagS": round(self._last_lag, 6),
                    "maxLagS": round(self._max_lag, 6),
                    "recentMaxLagS": round(recent_max, 6),
                    "casPending": self._cas_pending,
                    "creditStallS": round(self._credit_stall_last, 6)}


__all__ = ["Sentinel"]
