"""CLI entry: ``python -m scripts.dfslint [paths...]`` from the repo root.

Exit-code contract (stable for CI):
  0 — clean (no findings beyond the baseline)
  1 — findings
  2 — usage error (unknown flag, nonexistent path, malformed baseline)

Output formats: human text (default), ``--format json`` (alias
``--json``), ``--format sarif`` (SARIF 2.1.0 — GitHub code scanning
and every SARIF-aware CI viewer ingest it directly). ``--stats`` adds
the per-phase timing breakdown (walk/parse, phase-1 model, each rule,
audit) that the tier-1 wall-clock budget is asserted against.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path

from scripts.dfslint import analyze, load_baseline, save_baseline
from scripts.dfslint.core import DEFAULT_BASELINE
from scripts.dfslint.rules import ALL_RULES

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
# tier-1 scope: the package, the tooling, and the bench drivers
DEFAULT_ROOTS = ("dfs_tpu", "scripts", "bench*.py")

_SARIF_LEVEL = {"error": "error", "warning": "warning"}


def to_sarif(findings) -> dict:
    """Minimal valid SARIF 2.1.0 run: one driver, one rule entry per
    registered rule (plus DFS000), one result per finding."""
    rules = [{"id": "DFS000",
              "shortDescription": {"text": "parse error / stale "
                                           "suppression or baseline"}}]
    rules += [{"id": rid, "shortDescription": {"text": desc}}
              for rid, desc, _fn in ALL_RULES]
    results = []
    for f in findings:
        results.append({
            "ruleId": f.rule,
            "level": _SARIF_LEVEL.get(f.severity, "error"),
            "message": {"text": f.message},
            "partialFingerprints": {"dfslintKey/v1": f.key},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(1, f.line),
                               "startColumn": max(1, f.col + 1)},
                }}],
        })
    return {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                   "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "dfslint",
                "informationUri": "docs/lint.md",
                "rules": rules,
            }},
            "results": results,
        }],
    }


def changed_paths(repo_root: Path, base: str | None = None) -> set[str]:
    """Repo-relative paths touched per git: the worktree/index diff
    (plus, with ``base``, committed changes since that ref) and
    untracked files. Empty set = nothing changed. Raises ValueError
    when git itself fails (not a repo, bad ref) — the CLI maps that to
    exit 2 like any other usage error."""
    out: set[str] = set()
    cmds = [["git", "diff", "--name-only", "HEAD"],
            ["git", "ls-files", "--others", "--exclude-standard"]]
    if base:
        cmds.append(["git", "diff", "--name-only", f"{base}...HEAD"])
    for cmd in cmds:
        try:
            res = subprocess.run(cmd, cwd=repo_root, text=True,
                                 capture_output=True, check=True)
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            raise ValueError(
                f"--changed: {' '.join(cmd)} failed: "
                f"{detail.strip()}") from e
        out.update(line for line in res.stdout.splitlines() if line)
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m scripts.dfslint",
        description="multi-phase AST concurrency & invariant analyzer "
                    "for the async node runtime (rules DFS001-DFS013, "
                    "docs/lint.md)")
    ap.add_argument("paths", nargs="*", default=list(DEFAULT_ROOTS),
                    help="files/dirs/globs relative to the repo root "
                         f"(default: {' '.join(DEFAULT_ROOTS)})")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default=None,
                    help="output format (default text; sarif = SARIF "
                         "2.1.0 for CI ingestion)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="alias for --format json")
    ap.add_argument("--stats", action="store_true",
                    help="print the per-phase timing breakdown (text) "
                         "/ embed it (json)")
    ap.add_argument("--changed", nargs="?", const="", default=None,
                    metavar="BASE",
                    help="report only findings in git-changed files "
                         "(worktree + index vs HEAD, plus commits "
                         "since BASE when given) — the model is still "
                         "built whole-tree, so interprocedural facts "
                         "stay sound; for fast pre-commit runs")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help=f"baseline file (default {DEFAULT_BASELINE})")
    ap.add_argument("--update-baseline", action="store_true",
                    help="accept every current finding into the "
                         "baseline (pruning stale entries) and exit 0")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        # argparse exits 2 on usage error, 0 on --help: preserve both
        return int(e.code or 0)
    fmt = args.format or ("json" if args.as_json else "text")

    stats: dict = {}
    try:
        baseline = set() if args.update_baseline \
            else load_baseline(args.baseline)
        only = None
        if args.changed is not None:
            if args.update_baseline:
                print("dfslint: --changed cannot combine with "
                      "--update-baseline (a filtered run must not "
                      "rewrite the accepted set)", file=sys.stderr)
                return 2
            only = changed_paths(REPO_ROOT, args.changed or None)
            if not only:
                return 0   # nothing changed: trivially clean
        findings = analyze(args.paths or list(DEFAULT_ROOTS), REPO_ROOT,
                           baseline=baseline,
                           stats=stats if args.stats else None,
                           only_paths=only)
    except FileNotFoundError as e:
        print(f"dfslint: no such path: {e}", file=sys.stderr)
        return 2
    except ValueError as e:
        print(f"dfslint: {e}", file=sys.stderr)
        return 2

    if args.update_baseline:
        # DFS000 never enters the baseline: parse errors must be FIXED,
        # and accepting a stale-suppression/-baseline warning would
        # re-create exactly the rot the audit exists to surface
        keys = {f.key for f in findings if f.rule != "DFS000"}
        if args.paths and args.paths != list(DEFAULT_ROOTS):
            # narrowed scope: keep accepted keys the scan did not cover
            # — rewriting from a partial run would silently un-accept
            # every finding outside the given paths. A default-scope
            # update rewrites wholesale (it saw everything), which is
            # also how stale accepted keys get pruned.
            try:
                keys |= load_baseline(args.baseline)
            except ValueError as e:
                print(f"dfslint: {e}", file=sys.stderr)
                return 2
        path = save_baseline(keys, args.baseline)
        print(f"dfslint: baseline updated ({len(keys)} accepted "
              f"key(s)) -> {path}")
        return 0

    if fmt == "json":
        doc = {"findings": [f.to_json() for f in findings],
               "count": len(findings)}
        if args.stats:
            doc["stats"] = stats
        print(json.dumps(doc, indent=2, sort_keys=True))
    elif fmt == "sarif":
        print(json.dumps(to_sarif(findings), indent=2, sort_keys=True))
    else:
        for f in findings:
            print(f.render())
        if args.stats:
            phases = " ".join(f"{k}={v:.3f}s" for k, v in
                              stats.get("phases", {}).items())
            print(f"dfslint: {stats.get('files', 0)} files "
                  f"walk={stats.get('walkS', 0.0):.3f}s {phases} "
                  f"total={stats.get('totalS', 0.0):.3f}s",
                  file=sys.stderr)
        if findings:
            print(f"dfslint: {len(findings)} finding(s) — see "
                  "docs/lint.md for the rule catalogue and suppression "
                  "syntax", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
