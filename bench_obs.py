"""Observability benchmark -> OBS2_r11.json: the diagnosis plane's
acceptance evidence (journal + sentinels + tail-kept traces + doctor).

Three phases, in-process nodes, CPU CDC engine:

1. overhead — cached hot reads (SERVE_r06 phase-2b methodology:
   ``download_range`` on a warm SIEVE cache, ``readers`` concurrent
   whole-file reads x rounds), each read entered through a request span
   exactly like the HTTP layer does. Arms: EVERYTHING ON (default
   ObsConfig: trace ring, tail retention, flight-recorder journal,
   sentinels) vs EVERYTHING OFF (trace_ring=0, tail_keep=0,
   journal_bytes=0, sentinel_interval_s=0), alternated; the gated
   number is the median of per-repeat PAIRED overheads (adjacent arms
   share host conditions — see overhead_phase). Acceptance: the
   diagnosis plane adds <= 2%.
2. doctor — a 3-node cluster with node 3's dispatch delayed 1s per
   op (dominating the real per-call work); after traffic,
   ``GET /doctor`` on node 2 must name ``slow_peer`` with exactly
   node 3 as the offender.
3. tailkeep — a forced-slow download (peer dispatch lag makes the
   ``http./download`` request span exceed ``slow_span_s``): its trace
   id must (a) appear as an OpenMetrics exemplar on the download
   latency histogram, and (b) still be retrievable via ``/trace`` after
   enough ordinary traffic churned an ordinary trace out of the
   (deliberately small) span ring.

Usage: python bench_obs.py [file_bytes] [readers] [--tiny] [--out PATH]
Writes OBS2_r11.json (or --out) and prints it. OBS_r09.json (the r09
tracing evidence this bench's earlier life produced) stays committed.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import socket
import sys
import time
import urllib.request
from pathlib import Path

import numpy as np

from dfs_tpu.config import (CDCParams, ClusterConfig, NodeConfig,
                            ObsConfig, PeerAddr, ServeConfig)
from dfs_tpu.node.runtime import StorageNodeServer
from dfs_tpu.obs import new_span_id, new_trace_id

ART = "OBS2_r11.json"
CDC = CDCParams(min_size=2048, avg_size=8192, max_size=65536)

OBS_ALL_OFF = ObsConfig(trace_ring=0, tail_keep=0, journal_bytes=0,
                        sentinel_interval_s=0)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _mk_cluster(n: int, rf: int) -> ClusterConfig:
    ports = _free_ports(2 * n)
    peers = tuple(PeerAddr(node_id=i + 1, host="127.0.0.1",
                           port=ports[2 * i],
                           internal_port=ports[2 * i + 1])
                  for i in range(n))
    return ClusterConfig(peers=peers, replication_factor=rf)


async def _start(cluster: ClusterConfig, root: Path,
                 **cfg_kw) -> dict[int, StorageNodeServer]:
    nodes = {}
    for p in cluster.peers:
        cfg = NodeConfig(node_id=p.node_id, cluster=cluster,
                         data_root=root, fragmenter="cdc", cdc=CDC,
                         health_probe_s=0, **cfg_kw)
        n = StorageNodeServer(cfg)
        await n.start()
        nodes[p.node_id] = n
    return nodes


def _req(port: int, method: str, path: str, body: bytes | None = None,
         headers: dict | None = None) -> bytes:
    r = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                               data=body, method=method,
                               headers=headers or {})
    with urllib.request.urlopen(r, timeout=120) as resp:
        return resp.read()


# ------------------------------------------------------------------ #
# phase 1: everything-on overhead on cached hot reads
# ------------------------------------------------------------------ #

async def _hot_read_gibps(node: StorageNodeServer, file_id: str,
                          size: int, readers: int, rounds: int) -> float:
    """Aggregate GiB/s of concurrent cached whole-file range reads, each
    entered through a request span exactly like the HTTP layer."""
    async def read_once() -> None:
        with node.obs.request_span("http./download", latency=True):
            _, parts, _, _ = await node.download_range(file_id, 0, size - 1)
        assert sum(len(p) for p in parts) == size

    t0 = time.perf_counter()
    for _ in range(rounds):
        await asyncio.gather(*(read_once() for _ in range(readers)))
    dt = time.perf_counter() - t0
    return readers * rounds * size / dt / 2**30


async def overhead_phase(tmp: Path, data: bytes, readers: int,
                         rounds: int, repeats: int) -> dict:
    """Paired INTERLEAVED arms: the full diagnosis plane (default
    ObsConfig) vs everything off, identical node/workload otherwise.

    Both arms' nodes live in the SAME process with their caches warmed
    before any measurement, and repeats alternate arm order — a fresh
    process per arm measures mostly page-cache and scheduler luck on a
    small container (one such run showed a 23% swing BETWEEN two runs
    of the same arm), while interleaved same-process sampling isolates
    the per-read cost the gate is actually about."""
    serve = ServeConfig(cache_bytes=max(256 * 2**20, 4 * len(data)))
    size = len(data)
    arms: dict[str, StorageNodeServer] = {}
    files: dict[str, str] = {}
    results: dict[str, list[float]] = {"on": [], "off": []}
    try:
        for arm, obs_cfg in (("off", OBS_ALL_OFF), ("on", ObsConfig())):
            cluster = _mk_cluster(1, rf=1)
            nodes = await _start(cluster, tmp / f"hot_{arm}",
                                 serve=serve, obs=obs_cfg)
            arms[arm] = nodes[1]
            m, _ = await nodes[1].upload(data, "hot.bin")
            files[arm] = m.file_id
            await _hot_read_gibps(nodes[1], m.file_id, size, 4, 1)  # warm
        for rep in range(repeats):
            order = ("off", "on") if rep % 2 == 0 else ("on", "off")
            for arm in order:
                results[arm].append(await _hot_read_gibps(
                    arms[arm], files[arm], size, readers, rounds))
    finally:
        for node in arms.values():
            await node.stop()
    for arm in ("off", "on"):
        log(f"phase 1 arm={arm}: " + ", ".join(
            f"{x:.3f}" for x in results[arm]) + " GiB/s")
    on, off = max(results["on"]), max(results["off"])
    best_of_pct = (off - on) / off * 100.0
    # The gated estimator is the MEDIAN of per-repeat paired overheads:
    # the two arms of one repeat run back to back, so each pair shares
    # its moment's host conditions and pairing cancels the minutes-scale
    # load drift that best-of — comparing two lucky draws from
    # DIFFERENT repeats — cannot (per-sample swing on this shared
    # 1-core host is ±20%; bench.py's paired-slope median is the same
    # discipline). best_of_pct and the raw samples stay in the artifact
    # so the number can be recomputed from its own evidence.
    paired = sorted((o - n) / o * 100.0
                    for o, n in zip(results["off"], results["on"]))
    mid = len(paired) // 2
    overhead_pct = paired[mid] if len(paired) % 2 \
        else (paired[mid - 1] + paired[mid]) / 2.0
    return {"readers": readers, "rounds": rounds, "repeats": repeats,
            "diagnosis_on_gibps": round(on, 4),
            "diagnosis_off_gibps": round(off, 4),
            "samples_gibps": {arm: [round(x, 4) for x in results[arm]]
                              for arm in ("off", "on")},
            "best_of_pct": round(best_of_pct, 3),
            "overhead_pct": round(overhead_pct, 3),
            "within_2pct": overhead_pct <= 2.0}


# ------------------------------------------------------------------ #
# phase 2: the doctor names an injected slow peer
# ------------------------------------------------------------------ #

async def doctor_phase(tmp: Path, data: bytes, uploads: int) -> dict:
    cluster = _mk_cluster(3, rf=3)
    nodes = await _start(cluster, tmp / "doctor")
    try:
        real_dispatch = nodes[3]._dispatch

        # 1s, not something subtler: the lag must dominate real
        # per-call work (hash-echo verify, cold-start JIT — observed at
        # 150ms+ on a loaded host) or the slow peer hides under the 3x
        # rule threshold and the gate tests the weather
        async def laggy(header, body):
            await asyncio.sleep(1.0)
            return await real_dispatch(header, body)

        nodes[3]._dispatch = laggy
        for i in range(uploads):
            await nodes[1].upload(data + bytes([i % 256]), f"d{i}.bin")
        rep = json.loads((await asyncio.to_thread(
            _req, cluster.peers[1].port, "GET", "/doctor")).decode())
        slow = [f for f in rep["findings"] if f["rule"] == "slow_peer"]
        return {"injected_slow_peer": 3, "uploads": uploads,
                "peers_queried": len(rep["nodes"]),
                "findings": rep["findings"],
                "slow_peer_findings": slow,
                "named_correctly": bool(slow and slow[0]["peers"] == [3]
                                        and len(slow) == 1)}
    finally:
        for n in nodes.values():
            await n.stop()


# ------------------------------------------------------------------ #
# phase 3: tail retention + exemplars on a forced-slow download
# ------------------------------------------------------------------ #

async def tailkeep_phase(tmp: Path, data: bytes, churn: int) -> dict:
    # small ring so ordinary churn provably evicts; slow_span_s well
    # under the injected lag so the download pins
    obs_cfg = ObsConfig(trace_ring=64, slow_span_s=0.2)
    cluster = _mk_cluster(2, rf=2)
    nodes = await _start(cluster, tmp / "tail", obs=obs_cfg)
    try:
        m, _ = await nodes[1].upload(data, "slow.bin")

        # an ORDINARY (fast) download first: its trace should NOT
        # survive the churn — the control arm of tail retention
        port1 = cluster.peers[0].port
        ordinary_tid = new_trace_id()
        hdr = {"X-Dfs-Trace": f"{ordinary_tid}-{new_span_id()}"}
        await asyncio.to_thread(_req, port1, "GET",
                                f"/download?fileId={m.file_id}", None, hdr)

        # now force a SLOW download: delete node 1's local copies of the
        # file's FIRST chunks (the head of the stream, covered by the
        # request span) and lag node 2's dispatch — serving the request
        # now requires peer fetches that push http./download far past
        # slow_span_s
        slow_tid = new_trace_id()
        real_dispatch2 = nodes[2]._dispatch

        async def laggy2(header, body):
            await asyncio.sleep(0.4)
            return await real_dispatch2(header, body)

        nodes[2]._dispatch = laggy2
        all_digests = m.digests()
        for d in all_digests[: max(1, len(all_digests) // 4)]:
            nodes[1].store.chunks.delete(d)
        hdr_slow = {"X-Dfs-Trace": f"{slow_tid}-{new_span_id()}"}
        got = await asyncio.to_thread(
            _req, port1, "GET", f"/download?fileId={m.file_id}", None,
            hdr_slow)
        assert got == data, "forced-slow download not byte-identical"
        nodes[2]._dispatch = real_dispatch2

        # churn: ordinary traffic far beyond the 64-slot ring
        for _ in range(churn):
            await asyncio.to_thread(_req, port1, "GET", "/status")

        ordinary = json.loads((await asyncio.to_thread(
            _req, port1, "GET",
            f"/trace?traceId={ordinary_tid}&cluster=0")).decode())
        kept = json.loads((await asyncio.to_thread(
            _req, port1, "GET",
            f"/trace?traceId={slow_tid}")).decode())
        prom = (await asyncio.to_thread(
            _req, port1, "GET", "/metrics?format=prom")).decode()
        exemplar_hit = any(
            f'trace_id="{slow_tid}"' in line
            for line in prom.splitlines()
            if line.startswith("dfs_latency_seconds_bucket")
            and 'name="http./download"' in line)
        kept_names = sorted({s["name"] for s in kept["spans"]})
        return {
            "ring": obs_cfg.trace_ring, "churn_requests": churn,
            "slow_trace_id": slow_tid,
            "ordinary_trace_evicted": ordinary["spans"] == [],
            "slow_trace_spans_after_churn": len(kept["spans"]),
            "slow_trace_span_names": kept_names,
            "exemplar_on_download_histogram": exemplar_hit,
            "retained": bool(kept["spans"]
                             and "http./download" in kept_names),
        }
    finally:
        for n in nodes.values():
            await n.stop()


async def run(total: int, readers: int, tmp: Path, tiny: bool) -> dict:
    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, size=total, dtype=np.uint8).tobytes()
    out: dict = {"metric": "obs_diagnosis_plane", "round": 11,
                 "workload": {"file_bytes": total, "readers": readers,
                              "tiny": tiny,
                              "cdc": {"min": CDC.min_size,
                                      "avg": CDC.avg_size,
                                      "max": CDC.max_size}}}
    out["overhead"] = await overhead_phase(
        tmp, data, readers, rounds=1 if tiny else 12,
        repeats=2 if tiny else 9)
    log(f"phase 1: on {out['overhead']['diagnosis_on_gibps']} vs off "
        f"{out['overhead']['diagnosis_off_gibps']} GiB/s "
        f"({out['overhead']['overhead_pct']}% overhead)")
    out["doctor"] = await doctor_phase(tmp, data[:30_000],
                                       uploads=1 if tiny else 2)
    log(f"phase 2: slow_peer named_correctly="
        f"{out['doctor']['named_correctly']} "
        f"({len(out['doctor']['findings'])} finding(s))")
    # churn must exceed the phase's 64-slot ring with margin, or nothing
    # ordinary is evicted and retention proves nothing
    out["tailkeep"] = await tailkeep_phase(tmp, data[:256 * 1024],
                                           churn=150)
    log(f"phase 3: retained={out['tailkeep']['retained']} "
        f"exemplar={out['tailkeep']['exemplar_on_download_histogram']} "
        f"ordinary_evicted={out['tailkeep']['ordinary_trace_evicted']}")
    # --tiny exercises the phases + schema as a CI smoke; the ≤2%
    # overhead bound is the FULL run's gate (the committed artifact) —
    # at tiny scale (2 repeats, 1 round) arm noise on a small host
    # swings past the bound in both directions, so gating it there
    # would only test the weather
    overhead_ok = tiny or out["overhead"]["within_2pct"]
    out["ok"] = bool(overhead_ok
                     and out["doctor"]["named_correctly"]
                     and out["tailkeep"]["retained"]
                     and out["tailkeep"]["exemplar_on_download_histogram"]
                     and out["tailkeep"]["ordinary_trace_evicted"])
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("file_bytes", nargs="?", type=int, default=None,
                    help="hot-file size in bytes "
                         "(default: 32 MiB, 2 MiB with --tiny)")
    ap.add_argument("readers", nargs="?", type=int, default=None,
                    help="concurrent readers (default: 16, 4 with --tiny)")
    ap.add_argument("--tiny", action="store_true",
                    help="tier-1 smoke mode: seconds, doctor+tailkeep "
                         "gated, overhead reported but not gated")
    ap.add_argument("--out", default=None,
                    help=f"artifact path (default: {ART} next to this "
                         "script)")
    args = ap.parse_args(argv)
    tiny = args.tiny
    out_path = Path(args.out) if args.out \
        else Path(__file__).parent / ART
    total = args.file_bytes if args.file_bytes is not None \
        else (2 * 2**20 if tiny else 32 * 2**20)
    readers = args.readers if args.readers is not None \
        else (4 if tiny else 16)
    import tempfile

    with tempfile.TemporaryDirectory(prefix="bench_obs_") as tmp:
        out = asyncio.run(run(total, readers, Path(tmp), tiny))
    out_path.write_text(json.dumps(out, indent=2) + "\n")
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
