"""CLI — interactive menu parity with the reference client plus a scriptable
mode (the reference's pure interactivity is why it has zero automated tests,
SURVEY.md §4).

Interactive menu reproduces Client.java:36-40 exactly:
    0 Exit | 1 Test server | 2 List files | 3 Upload file | 4 Download file

Scriptable subcommands: serve, sidecar, status, list, upload, download,
delete, metrics, trace, events, doctor, census, df, menu.
"""

from __future__ import annotations

import argparse
import asyncio
import sys
from pathlib import Path

from dfs_tpu.cli.client import NodeClient
from dfs_tpu.config import (CDCParams, CensusConfig, ChaosConfig,
                            ClusterConfig, DurabilityConfig,
                            FragmenterConfig, IndexConfig, IngestConfig,
                            NodeConfig, ObsConfig, RingConfig,
                            ServeConfig, SimConfig, TierConfig)


def _client(args) -> NodeClient:
    return NodeClient(host=args.host, port=args.port)


def _smart_client(args):
    """--smart: build the SDK data-plane client (docs/client.md) from
    the --client-* knobs; everything still degrades to the coordinator
    path unless --client-no-fallback."""
    from dfs_tpu.client import SmartClient
    from dfs_tpu.config import ClientConfig

    cfg = ClientConfig(
        window=args.client_window,
        stripe=args.client_stripe,
        hedge_budget_per_s=args.client_hedge_budget,
        hedge_floor_s=args.client_hedge_floor,
        hedge_cap_s=args.client_hedge_cap,
        filter_max_age_s=args.client_filter_max_age,
        echo_cache_entries=args.client_echo_cache,
        fallback=not args.client_no_fallback,
    )
    return SmartClient(host=args.host, port=args.port, cfg=cfg)


def cmd_serve(args) -> int:
    from dfs_tpu.node.runtime import StorageNodeServer

    if args.cluster_config:
        cluster = ClusterConfig.from_file(args.cluster_config)
        if args.replication_factor is not None:
            print(f"warning: --replication-factor ignored; using "
                  f"{cluster.replication_factor} from {args.cluster_config}",
                  file=sys.stderr)
    else:
        cluster = ClusterConfig.localhost(
            n_nodes=args.nodes, base_port=args.base_port,
            base_internal_port=args.base_internal_port,
            replication_factor=args.replication_factor
            if args.replication_factor is not None else 2)
    cfg = NodeConfig(
        node_id=args.node_id, cluster=cluster,
        data_root=Path(args.data_root), fragmenter=args.fragmenter,
        sidecar_port=args.sidecar_port,
        cdc=CDCParams(min_size=args.min_chunk, avg_size=args.avg_chunk,
                      max_size=args.max_chunk),
        frag=FragmenterConfig(devices=args.cdc_devices,
                              region_bytes=args.cdc_region_bytes,
                              staging_buffers=args.cdc_staging_buffers),
        fixed_parts=args.fixed_parts,
        connect_timeout_s=args.connect_timeout,
        request_timeout_s=args.request_timeout,
        retries=args.rpc_retries,
        health_probe_s=args.probe_interval,
        write_quorum=args.write_quorum,
        serve=ServeConfig(cache_bytes=args.cache_bytes,
                          readahead_batches=args.readahead,
                          download_slots=args.download_slots,
                          upload_slots=args.upload_slots,
                          internal_slots=args.internal_slots,
                          queue_depth=args.queue_depth,
                          retry_after_s=args.retry_after,
                          default_deadline_s=args.default_deadline,
                          hedge_floor_s=args.hedge_floor,
                          hedge_cap_s=args.hedge_cap,
                          hedge_budget_per_s=args.hedge_budget),
        ingest=IngestConfig(window=args.ingest_window,
                            flush_bytes=args.ingest_flush_bytes,
                            credit_bytes=args.ingest_credit_bytes,
                            slice_inflight=args.replicate_inflight,
                            cas_io_threads=args.cas_io_threads),
        obs=ObsConfig(trace_ring=args.trace_ring,
                      slow_span_s=args.slow_span,
                      tail_keep=args.tail_keep,
                      journal_bytes=args.journal_bytes,
                      journal_segment_bytes=args.journal_segment_bytes,
                      sentinel_interval_s=args.sentinel_interval,
                      sentinel_lag_s=args.sentinel_lag),
        census=CensusConfig(
            history_interval_s=args.census_interval,
            history_slots=args.census_history_slots,
            history_coarse_every=args.census_coarse_every,
            history_coarse_slots=args.census_coarse_slots,
            max_listed=args.census_max_listed),
        durability=DurabilityConfig(mode=args.durability),
        ring=RingConfig(
            vnodes=args.ring_vnodes,
            members=args.ring_members,
            rebalance_credit_bytes=args.ring_rebalance_credit_bytes),
        index=IndexConfig(
            enabled=args.index,
            memtable_entries=args.index_memtable_entries,
            compact_runs=args.index_compact_runs,
            filter_bits_per_key=args.index_filter_bits,
            filter_sync_s=args.index_filter_sync,
            background_compact=args.index_background_compact,
            echo_cache_entries=args.index_echo_cache),
        tier=TierConfig(
            enabled=args.tier,
            hot_fraction=args.tier_hot_fraction,
            min_idle_s=args.tier_min_idle,
            scan_interval_s=args.tier_scan_interval,
            ec_k=args.tier_ec_k,
            demote_credit_bytes=args.tier_demote_credit_bytes,
            half_life_s=args.tier_half_life,
            promote_reads=args.tier_promote_reads,
            redemote_cooldown_s=args.tier_redemote_cooldown,
            ledger_entries=args.tier_ledger_entries),
        sim=SimConfig(
            enabled=args.sim,
            sketch_size=args.sim_sketch_size,
            bands=args.sim_bands,
            shingle_bytes=args.sim_shingle_bytes,
            max_candidates=args.sim_max_candidates,
            min_chunk_bytes=args.sim_min_chunk_bytes,
            min_savings_frac=args.sim_min_savings_frac,
            max_delta_depth=args.sim_max_delta_depth,
            devices=args.sim_devices,
            rematerialize_reads=args.sim_rematerialize_reads),
        chaos=ChaosConfig(
            enabled=args.chaos,
            seed=args.chaos_seed,
            rpc_delay_s=args.chaos_rpc_delay,
            rpc_delay_peers=args.chaos_rpc_delay_peers,
            rpc_drop_rate=args.chaos_rpc_drop_rate,
            partition=args.chaos_partition,
            rpc_truncate_rate=args.chaos_rpc_truncate_rate,
            serve_delay_s=args.chaos_serve_delay,
            disk_error_rate=args.chaos_disk_error_rate,
            disk_full=args.chaos_disk_full,
            disk_delay_s=args.chaos_disk_delay,
            crash_point=args.chaos_crash_point))

    async def run() -> None:
        from dfs_tpu.utils.aio import create_logged_task

        node = StorageNodeServer(cfg)
        await node.start()
        # strong refs: the event loop holds only weak task references, so
        # an unreferenced background task can be GC'd and silently
        # cancelled mid-sleep
        tasks: list[asyncio.Task] = []

        def periodic(interval: float, what: str, fn) -> None:
            if interval <= 0:
                return

            async def loop() -> None:
                while True:
                    await asyncio.sleep(interval)
                    try:
                        await fn()
                    except Exception as e:  # noqa: BLE001
                        node.log.warning("%s failed: %s", what, e)

            # retained ref + exception-logging done-callback: the
            # per-iteration catch above handles expected failures, the
            # callback makes an UNexpected loop death visible instead of
            # parking the exception on a task nobody ever awaits
            tasks.append(create_logged_task(loop(), node.log, what))

        async def do_repair() -> None:
            n = await node.repair_once()
            if n:
                node.log.info("repair: re-replicated %d chunks", n)

        async def do_scrub() -> None:
            res = await node.scrub_once()
            if res["corrupt"]:
                node.log.warning("scrub: %d corrupt chunks evicted",
                                 res["corrupt"])

        periodic(args.repair_interval, "repair", do_repair)
        periodic(args.scrub_interval, "scrub", do_scrub)
        await asyncio.Event().wait()  # serve forever

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


def cmd_sidecar(args) -> int:
    import time

    from dfs_tpu.sidecar.service import SidecarServer

    srv = SidecarServer(
        port=args.sidecar_port, fragmenter=args.fragmenter,
        cdc_params=CDCParams(min_size=args.min_chunk,
                             avg_size=args.avg_chunk,
                             max_size=args.max_chunk))
    srv.start()
    print(f"sidecar listening on 127.0.0.1:{srv.port} "
          f"(fragmenter={srv.fragmenter.name})", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
    return 0


def cmd_status(args) -> int:
    print(_client(args).status())
    return 0


def cmd_list(args) -> int:
    files = _client(args).list_files()
    if not files:
        print("(no files)")
    for i, f in enumerate(files, 1):
        print(f"{i}. {f.name}  id={f.file_id[:16]}…  "
              f"size={f.size}  chunks={f.chunks}")
    return 0


def _maybe_trace_id(args) -> str | None:
    """--trace: mint a client-side trace id the node(s) will tag every
    span of this request with — inspect afterwards via `trace <id>`."""
    if not getattr(args, "trace", False):
        return None
    from dfs_tpu.obs import new_trace_id

    return new_trace_id()


def cmd_upload(args) -> int:
    path = Path(args.file)
    data = path.read_bytes()
    ec = getattr(args, "ec", 0)
    trace_id = _maybe_trace_id(args)
    if getattr(args, "smart", False):
        if ec or getattr(args, "resume", False):
            print("--smart is mutually exclusive with --ec/--resume "
                  "(the SDK has its own dedup probe; EC needs the "
                  "whole-body coordinator path)", file=sys.stderr)
            return 2
        info = _smart_client(args).upload(data, name=path.name)
        print(f"Uploaded ({info['dataPlane']}): fileId={info['fileId']} "
              f"chunks={info['chunks']} "
              f"clientSent={info['clientBytesSent']}B of {len(data)}B")
        return 0
    if getattr(args, "resume", False):
        if ec:
            print("--ec and --resume are mutually exclusive "
                  "(parity stripes need the whole-body upload path)",
                  file=sys.stderr)
            return 2
        # chunk locally, probe, send only missing payloads (SURVEY §5.4)
        info = _client(args).upload_resume(data, name=path.name,
                                           trace_id=trace_id)
        tr = f" traceId={trace_id}" if trace_id else ""
        print(f"Uploaded (resume): fileId={info['fileId']} "
              f"chunks={info['chunks']} "
              f"clientSent={info['clientBytesSent']}B of {len(data)}B{tr}")
        return 0
    info = _client(args).upload(data, name=path.name, ec=ec,
                                trace_id=trace_id)
    extra = (f" ecParity={info['ecParityBytes']}B"
             if "ecParityBytes" in info else "")
    if trace_id:
        extra += f" traceId={trace_id}"
    print(f"Uploaded: fileId={info['fileId']} chunks={info['chunks']} "
          f"transferred={info.get('transferredBytes', '?')}B "
          f"dedupSkipped={info.get('dedupSkippedBytes', '?')}B{extra}")
    return 0


def cmd_download(args) -> int:
    c = _client(args)
    file_id = args.file_id
    trace_id = _maybe_trace_id(args)
    if getattr(args, "smart", False):
        sc = _smart_client(args)
        data = sc.download(file_id)
        plane = "legacy" if sc.counters["legacyDownloads"] else "smart"
        print(f"dataPlane={plane}")
    else:
        data = c.download(file_id, trace_id=trace_id)
    if trace_id:
        print(f"traceId={trace_id}")
    # Resolve the friendly name like the reference client (downloads/<name>,
    # Client.java:214-219).
    name = file_id
    for f in c.list_files():
        if f.file_id == file_id:
            name = f.name
            break
    out = Path(args.out or "downloads") / name
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_bytes(data)
    print(f"Saved {len(data)} bytes to {out}")
    return 0


def cmd_delete(args) -> int:
    print(_client(args).delete(args.file_id))
    return 0


def cmd_metrics(args) -> int:
    import json
    if getattr(args, "prom", False):
        print(_client(args).metrics_prom(), end="")
        return 0
    print(json.dumps(_client(args).metrics(), indent=2, sort_keys=True))
    return 0


def cmd_events(args) -> int:
    """Flight-recorder query: recent lifecycle events of one node
    (GET /events) — one line per event, oldest first."""
    data = _client(args).events(since=args.since, limit=args.limit)
    if not data.get("enabled", True):
        print("(journal disabled on this node)")
        return 0
    import datetime

    for ev in data.get("events", []):
        ts = datetime.datetime.fromtimestamp(
            ev.get("ts", 0.0)).strftime("%Y-%m-%d %H:%M:%S")
        etype = ev.get("type", "?")
        rest = {k: v for k, v in ev.items()
                if k not in ("ts", "type", "node", "trace")}
        trace = f" trace={ev['trace']}" if ev.get("trace") else ""
        extra = " ".join(f"{k}={v}" for k, v in sorted(rest.items()))
        print(f"{ts} node={ev.get('node', '?')} {etype} {extra}{trace}"
              .rstrip())
    if data.get("dropped"):
        print(f"(warning: {data['dropped']} events dropped at the "
              "bounded writer)", file=sys.stderr)
    if data.get("torn"):
        print(f"({data['torn']} torn/corrupt record(s) skipped)",
              file=sys.stderr)
    return 0


def cmd_doctor(args) -> int:
    """Cluster doctor: collect per-node snapshots and print the named
    pathologies with their evidence (GET /doctor)."""
    from dfs_tpu.obs.doctor import render_report

    report = _client(args).doctor(cluster=not args.local)
    print(render_report(report))
    if args.json:
        import json

        print(json.dumps(report, indent=2, sort_keys=True))
    # actionable findings (or unreachable peers) flip the exit code so
    # the doctor is scriptable as a health gate. info notes (e.g. the
    # doctor_error a single old-build peer's malformed snapshot earns)
    # are printed but must not fail a pathology-free cluster.
    sick = any(f.get("severity") in ("critical", "warning")
               for f in report.get("findings") or []) \
        or report.get("peersFailed", 0)
    return 1 if sick else 0


def cmd_census(args) -> int:
    """Replication-health census (GET /census): histogram + bounded
    under-replicated / orphaned / over-replicated lists. Scriptable as
    a data-health gate: exit 1 on findings or unreachable peers."""
    from dfs_tpu.obs.census import render_census

    report = _client(args).census(cluster=not args.local)
    print(render_census(report))
    if args.json:
        import json

        print(json.dumps(report, indent=2, sort_keys=True))
    sick = any(report.get(f"{k}Total") for k in
               ("underReplicated", "orphaned", "overReplicated")) \
        or report.get("peersFailed", 0)
    return 1 if sick else 0


def cmd_df(args) -> int:
    """Cluster capacity (the storage-native df(1)): per-node and
    cluster CAS bytes, disk headroom, dedup ratio — the capacity
    section of GET /census."""
    from dfs_tpu.obs.census import render_df

    report = _client(args).census(cluster=True)
    print(render_df(report))
    if report.get("peersFailed"):
        print(f"(warning: {report['peersFailed']} peer(s) unreachable "
              "— totals are partial)", file=sys.stderr)
    return 0


def cmd_ring(args) -> int:
    """Elastic membership admin (docs/membership.md): `ring status`
    renders the cluster's epoch/member/migration view; `ring
    add/drain/remove/reweight <node>` bumps the epoch on the contacted
    node, which pushes the new map to every peer and kicks the online
    rebalancer."""
    c = _client(args)
    if args.action == "status":
        st = c.ring_status()
        mode = st.get("mode", "?")
        lines = [f"ring epoch {st.get('epoch')} ({mode}"
                 + (f", {st.get('vnodes')} vnodes" if mode == "hash"
                    else "") + ")"
                 + (" — MIGRATING from epoch "
                    f"{st.get('previousEpoch')}"
                    if st.get("migrating") else "")]
        for m in st.get("members", []):
            w = m.get("weight", 1.0)
            lines.append(f"  node {m.get('nodeId')}: weight {w}"
                         + ("  (draining)" if w == 0 else ""))
        reb = st.get("rebalance") or {}
        if reb.get("bytesMoved"):
            lines.append(f"  rebalance: {reb['bytesMoved']} bytes "
                         f"moved, {reb.get('pushes', 0)} pushes, "
                         f"creditStallS={reb.get('creditStallS', 0)}, "
                         f"dualReadHits={reb.get('dualReadHits', 0)}")
        for nid, p in sorted((st.get("peers") or {}).items(),
                             key=lambda kv: int(kv[0])):
            if p is None:
                lines.append(f"  peer {nid}: NO ANSWER")
            elif p.get("epoch") != st.get("epoch") or p.get("migrating"):
                lines.append(f"  peer {nid}: epoch {p.get('epoch')}"
                             + (" (migrating)" if p.get("migrating")
                                else ""))
        print("\n".join(lines))
        if st.get("peersFailed"):
            print(f"(warning: {st['peersFailed']} peer(s) unreachable "
                  "— view is partial)", file=sys.stderr)
        # scriptable: a split epoch view or unreachable peer exits 1
        split = any(p is not None and p.get("epoch") != st.get("epoch")
                    for p in (st.get("peers") or {}).values())
        return 1 if split or st.get("peersFailed") else 0
    out = c.ring_admin(args.action, node_id=args.node,
                       weight=args.weight)
    print(f"ring epoch {out.get('epoch')} installed "
          f"({args.action} node {args.node}); pushed to: "
          + ", ".join(f"{k}={'ok' if v else 'FAILED'}"
                      for k, v in sorted(
                          (out.get('pushed') or {}).items(),
                          key=lambda kv: int(kv[0]))))
    return 0


def cmd_trace(args) -> int:
    """Stitch + render one distributed trace (docs/observability.md):
    the contacted node gathers every peer's spans for the id and this
    renders the cross-node tree with a slow-span log on top."""
    from dfs_tpu.obs.stitch import render_tree

    data = _client(args).trace(args.trace_id)
    slow = args.slow if args.slow is not None \
        else float(data.get("slowSpanS", 1.0))
    print(render_tree(data.get("spans", []), slow_s=slow))
    if data.get("peersFailed"):
        print(f"(warning: {data['peersFailed']} peer(s) unreachable — "
              "trace may be partial)", file=sys.stderr)
    return 0


def cmd_menu(args) -> int:
    """Interactive loop, Client.java:29-82 parity."""
    while True:
        print("\n=== Distributed File Storage (TPU) ===\n"
              "0. Exit\n1. Test server\n2. List files\n"
              "3. Upload file\n4. Download file")
        try:
            choice = input("> ").strip()
        except EOFError:
            return 0
        try:
            if choice == "0":
                return 0
            elif choice == "1":
                args.port = _ask_port(args.port)
                print(_client(args).status())
            elif choice == "2":
                args.port = _ask_port(args.port)
                cmd_list(args)
            elif choice == "3":
                args.port = _ask_port(args.port)
                directory = input("Directory [.]: ").strip() or "."
                files = sorted(p for p in Path(directory).iterdir()
                               if p.is_file())
                if not files:
                    print("(no files)")
                    continue
                for i, p in enumerate(files, 1):
                    print(f"{i}. {p.name} ({p.stat().st_size} bytes)")
                idx = int(input("File #: ")) - 1
                args.file = str(files[idx])
                cmd_upload(args)
            elif choice == "4":
                args.port = _ask_port(args.port)
                files = _client(args).list_files()
                for i, f in enumerate(files, 1):
                    print(f"{i}. {f.name}")
                if not files:
                    print("(no files)")
                    continue
                idx = int(input("File #: ")) - 1
                args.file_id = files[idx].file_id
                args.out = None
                cmd_download(args)
            else:
                print("Invalid option")
        except Exception as e:  # noqa: BLE001 - per-iteration catch, Client.java:77-80
            print(f"Error: {e}")


def _ask_port(default: int) -> int:
    """Port prompt with fallback, Client.java:226-237 parity."""
    raw = input(f"Node port [{default}]: ").strip()
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="dfs-tpu", description="TPU-native distributed file storage")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=5001)
    sub = ap.add_subparsers(dest="cmd", required=True)

    serve = sub.add_parser("serve", help="run a storage node")
    serve.add_argument("--node-id", type=int, required=True)
    serve.add_argument("--cluster-config", default=None,
                       help="JSON/TOML cluster membership file (overrides "
                            "--nodes/--base-port/--replication-factor)")
    serve.add_argument("--nodes", type=int, default=5)
    serve.add_argument("--base-port", type=int, default=5001)
    serve.add_argument("--base-internal-port", type=int, default=6001)
    serve.add_argument("--replication-factor", type=int, default=None)
    serve.add_argument("--data-root", default="data")
    serve.add_argument(
        "--fragmenter", default="auto",
        choices=["auto", "fixed", "cdc", "cdc-tpu", "cdc-aligned",
                 "cdc-aligned-tpu", "cdc-anchored", "cdc-anchored-tpu"],
        help="default 'auto': the flagship anchored pipeline — TPU device "
             "path when a TPU is present, CPU oracle otherwise")
    serve.add_argument("--cdc-devices", type=int, default=0,
                       help="shard 'cdc' / 'cdc-anchored' streaming "
                            "regions over N JAX devices (0/1 = single-"
                            "device; boundaries are byte-identical "
                            "either way)")
    serve.add_argument("--cdc-region-bytes", type=int, default=0,
                       help="fixed device-region size for sharded CDC "
                            "(0 = devices * 1 MiB rolling / 64 MiB "
                            "anchored)")
    serve.add_argument("--cdc-staging-buffers", type=int, default=2,
                       help="host staging buffers the sharded anchored "
                            "walk cycles through (2 = double-buffered "
                            "staging/compute overlap, 1 = serial)")
    serve.add_argument("--min-chunk", type=int, default=2048)
    serve.add_argument("--avg-chunk", type=int, default=8192)
    serve.add_argument("--max-chunk", type=int, default=65536)
    serve.add_argument("--fixed-parts", type=int, default=5,
                       help="FixedFragmenter part count (reference "
                            "parity: TOTAL_NODES=5)")
    serve.add_argument("--connect-timeout", type=float, default=2.0,
                       help="per-attempt peer connect timeout (s)")
    serve.add_argument("--request-timeout", type=float, default=10.0,
                       help="per-attempt peer request timeout (s); bulk "
                            "transfers add a size-derived margin")
    serve.add_argument("--rpc-retries", type=int, default=3,
                       help="peer call attempts before a peer counts "
                            "as unreachable")
    serve.add_argument("--probe-interval", type=float, default=5.0,
                       help="seconds between peer health probes; 0 = "
                            "data-path feedback only (no probe loop)")
    serve.add_argument("--write-quorum", type=int, default=2,
                       help="copies (incl. local) an upload needs "
                            "before it acknowledges")
    serve.add_argument("--retry-after", type=float, default=1.0,
                       help="Retry-After seconds advertised on 503 "
                            "shed responses")
    serve.add_argument("--repair-interval", type=float, default=30.0)
    serve.add_argument("--scrub-interval", type=float, default=3600.0,
                       help="seconds between local integrity sweeps "
                            "(re-hash every chunk; 0 disables)")
    serve.add_argument("--cache-bytes", type=int, default=0,
                       help="hot-chunk cache budget (serving tier); "
                            "0 disables the cache + single-flight")
    serve.add_argument("--readahead", type=int, default=0,
                       help="streamed-download readahead depth (batches)")
    serve.add_argument("--download-slots", type=int, default=0,
                       help="concurrent download budget; 0 = unbounded")
    serve.add_argument("--upload-slots", type=int, default=0,
                       help="concurrent upload budget; 0 = unbounded")
    serve.add_argument("--internal-slots", type=int, default=0,
                       help="concurrent storage-plane bulk-op budget "
                            "(store/get chunks); 0 = unbounded")
    serve.add_argument("--queue-depth", type=int, default=64,
                       help="waiters beyond the slots before 503 shedding")
    serve.add_argument("--default-deadline", type=float, default=0.0,
                       help="end-to-end deadline (seconds) stamped on "
                            "HTTP requests without an X-Dfs-Deadline "
                            "header; 0 = none (docs/serve.md)")
    serve.add_argument("--hedge-floor", type=float, default=0.02,
                       help="minimum hedged-read delay (seconds) before "
                            "a second replica is asked")
    serve.add_argument("--hedge-cap", type=float, default=0.5,
                       help="maximum hedged-read delay (seconds)")
    serve.add_argument("--hedge-budget", type=float, default=0.0,
                       help="hedge token-bucket refill per second; "
                            "0 disables hedged reads (the default)")
    serve.add_argument("--sidecar-port", type=int, default=None,
                       help="delegate chunk+hash to a running sidecar "
                            "process (overrides --fragmenter)")
    serve.add_argument("--ingest-window", type=int, default=2,
                       help="streaming-ingest placement batches in "
                            "flight (1 = serial write path)")
    serve.add_argument("--ingest-flush-bytes", type=int,
                       default=32 * 1024 * 1024,
                       help="streaming-ingest placement batch size")
    serve.add_argument("--ingest-credit-bytes", type=int,
                       default=64 * 1024 * 1024,
                       help="byte budget of produced-but-unplaced chunks "
                            "(fragmenter backpressure)")
    serve.add_argument("--replicate-inflight", type=int, default=2,
                       help="replication slices in flight per peer "
                            "(1 = serial slices)")
    serve.add_argument("--cas-io-threads", type=int, default=4,
                       help="async CAS tier worker threads (local chunk "
                            "file I/O off the event loop)")
    serve.add_argument("--trace-ring", type=int, default=2048,
                       help="finished-span ring capacity (distributed "
                            "tracing); 0 disables tracing entirely")
    serve.add_argument("--slow-span", type=float, default=1.0,
                       help="slow threshold (s): trace stitcher slow "
                            "log AND the tail-retention outlier "
                            "detector")
    serve.add_argument("--tail-keep", type=int, default=256,
                       help="spans of slow/errored traces pinned "
                            "across ring churn; 0 disables tail "
                            "retention")
    serve.add_argument("--journal-bytes", type=int,
                       default=16 * 1024 * 1024,
                       help="flight-recorder on-disk budget (JSONL "
                            "event journal); 0 disables the journal")
    serve.add_argument("--journal-segment-bytes", type=int,
                       default=2 * 1024 * 1024,
                       help="journal segment rotation size")
    serve.add_argument("--sentinel-interval", type=float, default=1.0,
                       help="loop-lag/stall sentinel sampling period "
                            "(s); 0 disables sentinels")
    serve.add_argument("--sentinel-lag", type=float, default=0.25,
                       help="event-loop lag (s) above which the "
                            "sentinel journals a loop_lag incident")
    serve.add_argument("--census-interval", type=float, default=10.0,
                       help="metrics-history sample period (s) for the "
                            "census/capacity plane; 0 disables the "
                            "sampler (census queries still work)")
    serve.add_argument("--census-history-slots", type=int, default=360,
                       help="fine-resolution history buckets kept per "
                            "series")
    serve.add_argument("--census-coarse-every", type=int, default=30,
                       help="fine steps folded into one coarse history "
                            "bucket")
    serve.add_argument("--census-coarse-slots", type=int, default=288,
                       help="coarse-resolution history buckets kept "
                            "per series")
    serve.add_argument("--census-max-listed", type=int, default=64,
                       help="digests listed per census finding "
                            "category (under-replicated / orphaned / "
                            "over-replicated)")
    serve.add_argument("--durability", default="fsync",
                       choices=["fsync", "none"],
                       help="'fsync' (default): chunk + manifest writes "
                            "barrier file and directory before an "
                            "upload acks (crash-durable); 'none': bare "
                            "atomic renames (pre-r13 behavior)")
    serve.add_argument("--ring-vnodes", type=int, default=0,
                       help="virtual nodes per unit weight on the "
                            "consistent-hash membership ring; 0 "
                            "(default) = static legacy placement, "
                            "byte-stable with pre-r14 stores")
    serve.add_argument("--ring-members", default="",
                       help="csv node ids owning digest space at "
                            "epoch 0 (others are reachable standbys "
                            "until `ring add`); empty = every peer")
    serve.add_argument("--ring-rebalance-credit-bytes", type=int,
                       default=8 * 1024 * 1024,
                       help="online-rebalancer bandwidth bound "
                            "(payload bytes/s per node); 0 = "
                            "unthrottled")
    serve.add_argument("--index", action="store_true",
                       help="enable the dedup/index plane "
                            "(docs/index.md): persistent log-"
                            "structured digest index + peer-existence "
                            "filters; without this flag local "
                            "existence stays one stat per digest and "
                            "placement probes every digest over RPC")
    serve.add_argument("--index-memtable-entries", type=int,
                       default=65536,
                       help="in-memory index entries before a flush "
                            "to a sorted on-disk run")
    serve.add_argument("--index-compact-runs", type=int, default=4,
                       help="sorted runs before a full compaction "
                            "folds them into one")
    serve.add_argument("--index-filter-bits", type=int, default=10,
                       help="peer-existence filter bloom bits per "
                            "key; 0 = no filters (local index only)")
    serve.add_argument("--index-filter-sync", type=float, default=5.0,
                       help="peer-filter gossip cadence (s); 0 = no "
                            "background filter exchange")
    serve.add_argument("--index-background-compact", action="store_true",
                       help="run full index compactions on a dedicated "
                            "thread instead of the CAS workers (stall "
                            "attribution in /metrics index.compactStallS)")
    serve.add_argument("--index-echo-cache", type=int, default=0,
                       help="per-peer echo-confirmed existence cache "
                            "entries (0 = off): a digest whose hash-echo "
                            "was confirmed this ring epoch skips even "
                            "the trust-verification probe on re-upload")
    serve.add_argument("--tier", action="store_true",
                       help="enable the hot/cold tiering plane "
                            "(docs/tiering.md): temperature-driven "
                            "demotion of cold files from full "
                            "replication to EC stripes, with "
                            "transparent reads and read-driven "
                            "promotion")
    serve.add_argument("--tier-hot-fraction", type=float, default=0.1,
                       help="fraction of referenced bytes kept fully "
                            "replicated (the hot byte budget); files "
                            "past the temperature knee are "
                            "cold-eligible")
    serve.add_argument("--tier-min-idle", type=float, default=300.0,
                       help="seconds a file must go unread before it "
                            "may be demoted, however cold it ranks")
    serve.add_argument("--tier-scan-interval", type=float, default=0.0,
                       help="demotion scan cadence (s); 0 = manual "
                            "scans only (POST /tier)")
    serve.add_argument("--tier-ec-k", type=int, default=4,
                       help="data chunks per parity stripe for demoted "
                            "files (storage overhead ~(k+2)/k; needs "
                            "k+2 ring members)")
    serve.add_argument("--tier-demote-credit-bytes", type=int,
                       default=8 * 1024 * 1024,
                       help="demotion/promotion byte budget per second "
                            "(0 = unmetered) — background tiering must "
                            "not starve user traffic")
    serve.add_argument("--tier-half-life", type=float, default=3600.0,
                       help="read-heat half-life (s): each read adds "
                            "1.0 and the sum halves every half-life")
    serve.add_argument("--tier-promote-reads", type=float, default=2.0,
                       help="decayed heat at which a cold file "
                            "re-materializes replicated")
    serve.add_argument("--tier-redemote-cooldown", type=float,
                       default=0.0,
                       help="seconds a freshly-promoted file sits out "
                            "demotion scans (re-demotion hysteresis: a "
                            "file flapping around the promote threshold "
                            "must not churn encode/decode; 0 = off)")
    serve.add_argument("--tier-ledger-entries", type=int, default=65536,
                       help="bounded temperature-ledger size (stalest "
                            "digests evict first — eviction reads as "
                            "cold)")
    serve.add_argument("--sim", action="store_true",
                       help="enable the similarity compression plane "
                            "(docs/similarity.md): min-hash sketches on "
                            "ingest, LSH candidate lookup, and "
                            "delta-encoded chunk storage against "
                            "similar resident bases, transparent on "
                            "read")
    serve.add_argument("--sim-sketch-size", type=int, default=16,
                       help="min-hash lanes per sketch (more = finer "
                            "similarity resolution, linearly more "
                            "sketch compute)")
    serve.add_argument("--sim-bands", type=int, default=4,
                       help="LSH bands the sketch folds into (must "
                            "divide the sketch size; more bands = more "
                            "recall, more candidates)")
    serve.add_argument("--sim-shingle-bytes", type=int, default=8,
                       help="bytes per rolling shingle the sketch "
                            "hashes over")
    serve.add_argument("--sim-max-candidates", type=int, default=8,
                       help="bounded candidate-set size per lookup — "
                            "each candidate costs a base read + trial "
                            "encode on the CAS worker")
    serve.add_argument("--sim-min-chunk-bytes", type=int, default=4096,
                       help="chunks below this skip sketching entirely "
                            "(delta headers would eat the savings)")
    serve.add_argument("--sim-min-savings-frac", type=float, default=0.5,
                       help="store a delta only when its size is at or "
                            "below this fraction of the raw chunk")
    serve.add_argument("--sim-max-delta-depth", type=int, default=3,
                       help="longest base chain a reconstruction may "
                            "walk (caps read amplification)")
    serve.add_argument("--sim-devices", type=int, default=0,
                       help="devices to shard sketch batches over "
                            "(0/1 = host oracle; >1 = chunks-over-dp "
                            "on the mesh, byte-identical output)")
    serve.add_argument("--sim-rematerialize-reads", type=int, default=0,
                       help="reconstructions after which a hot delta is "
                            "re-materialized as a raw chunk (0 = never)")
    serve.add_argument("--chaos", action="store_true",
                       help="enable the fault-injection plane "
                            "(docs/chaos.md): the knobs below apply "
                            "and POST /chaos re-scripts them live; "
                            "without this flag NO injector exists and "
                            "every knob is ignored")
    serve.add_argument("--chaos-seed", type=int, default=0,
                       help="fault-decision RNG seed (xor'd with the "
                            "node id: per-node deterministic schedules)")
    serve.add_argument("--chaos-rpc-delay", type=float, default=0.0,
                       help="injected latency (s) before outbound "
                            "storage-plane calls")
    serve.add_argument("--chaos-rpc-delay-peers", default="",
                       help="csv node ids the rpc delay applies to "
                            "(empty = every peer)")
    serve.add_argument("--chaos-rpc-drop-rate", type=float, default=0.0,
                       help="probability an outbound call's connection "
                            "is dropped before the frame is sent")
    serve.add_argument("--chaos-partition", default="",
                       help="csv node ids unreachable FROM this node "
                            "(one-way; configure one side only for an "
                            "asymmetric partition)")
    serve.add_argument("--chaos-rpc-truncate-rate", type=float,
                       default=0.0,
                       help="probability an outbound frame is cut off "
                            "mid-body and the connection closed")
    serve.add_argument("--chaos-serve-delay", type=float, default=0.0,
                       help="injected delay (s) before serving each "
                            "inbound storage-plane op (a slow node)")
    serve.add_argument("--chaos-disk-error-rate", type=float,
                       default=0.0,
                       help="probability a CAS put/get raises EIO")
    serve.add_argument("--chaos-disk-full", action="store_true",
                       help="every CAS put raises ENOSPC (uploads "
                            "degrade to HTTP 507; reads keep working)")
    serve.add_argument("--chaos-disk-delay", type=float, default=0.0,
                       help="injected delay (s) before every CAS op "
                            "(slow disk; runs on the CAS workers)")
    serve.add_argument("--chaos-crash-point", default="",
                       help="registered crash-point name (see "
                            "dfs_tpu.chaos.CRASH_POINTS): the process "
                            "SIGKILLs itself the first time execution "
                            "reaches it")
    serve.set_defaults(fn=cmd_serve)

    sc = sub.add_parser("sidecar", help="run the chunk+hash sidecar service")
    sc.add_argument("--sidecar-port", type=int, default=50151)
    sc.add_argument(
        "--fragmenter", default="auto",
        choices=["auto", "fixed", "cdc", "cdc-tpu", "cdc-aligned",
                 "cdc-aligned-tpu", "cdc-anchored", "cdc-anchored-tpu"])
    sc.add_argument("--min-chunk", type=int, default=2048)
    sc.add_argument("--avg-chunk", type=int, default=8192)
    sc.add_argument("--max-chunk", type=int, default=65536)
    sc.set_defaults(fn=cmd_sidecar)

    sub.add_parser("status").set_defaults(fn=cmd_status)
    sub.add_parser("list").set_defaults(fn=cmd_list)
    def _add_client_flags(p):
        """--smart data-plane knobs (ClientConfig, docs/client.md)."""
        p.add_argument("--smart", action="store_true",
                       help="use the SDK data plane: chunk+hash locally, "
                            "consult peer-existence filters, stripe "
                            "directly to the rf ring owners, one-call "
                            "commit; falls back to the coordinator path "
                            "on old servers / epoch churn")
        p.add_argument("--client-window", type=int, default=2,
                       help="store slices in flight per peer")
        p.add_argument("--client-stripe", type=int, default=4,
                       help="concurrent read batches across owners")
        p.add_argument("--client-hedge-budget", type=float, default=0.0,
                       help="hedged read/write budget (fires/s); 0 = "
                            "no client-side hedging")
        p.add_argument("--client-hedge-floor", type=float, default=0.05,
                       help="minimum hedge delay (s)")
        p.add_argument("--client-hedge-cap", type=float, default=1.0,
                       help="maximum hedge delay (s)")
        p.add_argument("--client-filter-max-age", type=float, default=30.0,
                       help="peer-existence filter freshness bound (s); "
                            "older replicas degrade to probes")
        p.add_argument("--client-echo-cache", type=int, default=4096,
                       help="echo-confirmed existence cache entries per "
                            "peer (0 = always run the trust probe)")
        p.add_argument("--client-no-fallback", action="store_true",
                       help="raise instead of degrading to the legacy "
                            "coordinator path (testing/benchmarks)")

    up = sub.add_parser("upload")
    up.add_argument("file")
    up.add_argument("--resume", action="store_true",
                    help="probe the cluster and send only missing chunks")
    up.add_argument("--ec", type=int, default=0, metavar="K",
                    help="erasure-code with K data shards + P/Q parity "
                         "per stripe (needs K+2 cluster nodes; any two "
                         "lost shards per stripe are recoverable)")
    up.add_argument("--trace", action="store_true",
                    help="tag the request with a fresh trace id "
                         "(printed) for `trace <id>` inspection")
    _add_client_flags(up)
    up.set_defaults(fn=cmd_upload)
    down = sub.add_parser("download")
    down.add_argument("file_id")
    down.add_argument("--out", default=None)
    down.add_argument("--trace", action="store_true",
                      help="tag the request with a fresh trace id "
                           "(printed) for `trace <id>` inspection")
    _add_client_flags(down)
    down.set_defaults(fn=cmd_download)
    rm = sub.add_parser("delete")
    rm.add_argument("file_id")
    rm.set_defaults(fn=cmd_delete)
    mt = sub.add_parser("metrics")
    mt.add_argument("--prom", action="store_true",
                    help="Prometheus text exposition instead of JSON")
    mt.set_defaults(fn=cmd_metrics)
    ev = sub.add_parser("events",
                        help="recent flight-recorder lifecycle events")
    ev.add_argument("--since", type=float, default=0.0,
                    help="unix-seconds lower bound (default: all "
                         "retained)")
    ev.add_argument("--limit", type=int, default=256,
                    help="newest events returned (1..4096)")
    ev.set_defaults(fn=cmd_events)
    dr = sub.add_parser("doctor",
                        help="cluster health diagnosis (named "
                             "pathologies + evidence)")
    dr.add_argument("--local", action="store_true",
                    help="diagnose the contacted node only (no peer "
                         "fan-out)")
    dr.add_argument("--json", action="store_true",
                    help="also print the full report as JSON")
    dr.set_defaults(fn=cmd_doctor)
    cn = sub.add_parser("census",
                        help="replication-health census (digest "
                             "copies histogram + under-replicated/"
                             "orphaned/over-replicated findings)")
    cn.add_argument("--local", action="store_true",
                    help="inventory the contacted node only (no peer "
                         "fan-out)")
    cn.add_argument("--json", action="store_true",
                    help="also print the full report as JSON")
    cn.set_defaults(fn=cmd_census)
    df = sub.add_parser("df",
                        help="cluster capacity: per-node CAS bytes, "
                             "disk headroom, dedup ratio")
    df.set_defaults(fn=cmd_df)
    rg = sub.add_parser("ring",
                        help="elastic membership: show or change the "
                             "placement ring (epoch-versioned; "
                             "changes rebalance online)")
    rg.add_argument("action",
                    choices=["status", "add", "drain", "remove",
                             "reweight"])
    rg.add_argument("node", type=int, nargs="?", default=None,
                    help="target node id (required for every action "
                         "but status)")
    rg.add_argument("--weight", type=float, default=None,
                    help="member weight (add/reweight); default 1.0 "
                         "on add")
    rg.set_defaults(fn=cmd_ring)
    tr = sub.add_parser("trace",
                        help="render a stitched cross-node trace")
    tr.add_argument("trace_id")
    tr.add_argument("--slow", type=float, default=None,
                    help="slow-span threshold (s); default: the node's "
                         "configured slow_span_s")
    tr.set_defaults(fn=cmd_trace)
    sub.add_parser("menu").set_defaults(fn=cmd_menu)
    return ap


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
