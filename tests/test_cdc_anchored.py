"""Anchored two-level CDC (v3): oracle properties, device parity, and the
shift-resilience the aligned v2 grid lacks."""

import hashlib

import numpy as np
import pytest

from dfs_tpu.ops.cdc_anchored import (TILE_BYTES, AnchoredCdcParams,
                                      anchor_hash_np, batch_chunks_anchored,
                                      chunk_file_anchored_np,
                                      chunk_spans_anchored_np,
                                      kept_anchors_np, select_segments)
from dfs_tpu.ops.cdc_v2 import AlignedCdcParams

SMALL = AnchoredCdcParams(
    chunk=AlignedCdcParams(min_blocks=2, avg_blocks=4, max_blocks=16,
                           strip_blocks=64),           # 4 KiB lanes
    seg_min=2048, seg_max=4096, seg_mask=2047)


def corpus(n, seed=0):
    return np.random.default_rng(seed).integers(0, 256, size=n,
                                                dtype=np.uint8)


# ---------------------------------------------------------------- oracle --

def test_anchor_hash_window_is_8_bytes():
    # changing byte p-8 must not affect h_p; changing p-7..p must
    data = corpus(64, seed=1)
    h = anchor_hash_np(data, SMALL)
    p = 40
    d2 = data.copy()
    d2[p - 8] ^= 0xFF
    assert anchor_hash_np(d2, SMALL)[p] == h[p]
    d3 = data.copy()
    d3[p - 7] ^= 0xFF
    assert anchor_hash_np(d3, SMALL)[p] != h[p]


def test_kept_anchors_two_per_tile():
    data = corpus(200000, seed=2)
    kept = kept_anchors_np(data, SMALL)
    tiles = kept // TILE_BYTES
    counts = np.bincount(tiles)
    assert counts.max() <= 2
    assert np.all(np.diff(kept) > 0)
    # the rule keeps the FIRST two of each tile: every kept pair must be
    # the two smallest qualifying positions of its tile
    from dfs_tpu.ops.cdc_anchored import anchor_hash_np
    hit = (anchor_hash_np(data, SMALL) & np.uint32(SMALL.seg_mask)) == 0
    pos = np.flatnonzero(hit)
    for t in np.unique(tiles):
        in_tile = pos[pos // TILE_BYTES == t]
        expect = in_tile[:2]
        got = kept[tiles == t]
        assert np.array_equal(got, expect)


def test_segments_respect_bounds():
    data = corpus(300000, seed=3)
    bounds = select_segments(kept_anchors_np(data, SMALL),
                             data.shape[0], SMALL)
    assert bounds[-1] == data.shape[0]
    prev = 0
    for b in bounds[:-1].tolist():
        assert SMALL.seg_min <= b - prev <= SMALL.seg_max
        prev = b
    assert bounds[-1] - prev <= SMALL.seg_max


def test_spans_tile_stream_and_match_hashlib():
    for n in (1, 63, 65, 5000, 100001):
        data = corpus(n, seed=n)
        spans = chunk_spans_anchored_np(data, SMALL)
        assert spans[0][0] == 0
        assert sum(ln for _, ln in spans) == n
        for (o1, l1), (o2, _) in zip(spans, spans[1:]):
            assert o1 + l1 == o2
    chunks = chunk_file_anchored_np(corpus(50000, seed=9), SMALL)
    data = corpus(50000, seed=9)
    for o, ln, dg in chunks:
        assert dg == hashlib.sha256(data[o:o + ln].tobytes()).hexdigest()


def test_shift_resilience_vs_aligned():
    """The defining property: after an unaligned insertion, most chunks
    must still dedup (the v2 aligned grid loses everything downstream)."""
    base = corpus(300000, seed=4)
    edited = np.concatenate(
        [base[:50001], corpus(77, seed=5), base[50001:]])
    a = {dg for _, _, dg in chunk_file_anchored_np(base, SMALL)}
    b = [(o, ln, dg) for o, ln, dg in chunk_file_anchored_np(edited, SMALL)]
    shared = sum(ln for _, ln, dg in b if dg in a)
    assert shared / edited.shape[0] > 0.85, \
        f"only {shared / edited.shape[0]:.0%} of bytes deduped after insert"


# ---------------------------------------------------------- device parity --

@pytest.mark.parametrize("n", [1, 63, 4096, 5000, 100001, 300000])
def test_device_matches_oracle(n):
    data = corpus(n, seed=n + 100)
    got = batch_chunks_anchored(data, SMALL, lane_multiple=8)
    want = chunk_file_anchored_np(data, SMALL)
    assert got == want


def test_device_low_entropy():
    # all-zeros: anchor hash is constant; whatever it decides, device and
    # oracle must agree, max-size forcing must bound segments
    data = np.zeros((100000,), dtype=np.uint8)
    got = batch_chunks_anchored(data, SMALL, lane_multiple=8)
    want = chunk_file_anchored_np(data, SMALL)
    assert got == want
    # repeating pattern (anchor-dense)
    data = np.tile(corpus(256, seed=6), 400)
    assert batch_chunks_anchored(data, SMALL, lane_multiple=8) == \
        chunk_file_anchored_np(data, SMALL)


def test_device_tail_digests():
    # segment tails end in partial blocks — the device finalize path must
    # agree with hashlib for every chunk, including tails >= 56 bytes mod 64
    for seed in range(3):
        data = corpus(37777 + seed * 1111, seed=seed + 20)
        for o, ln, dg in batch_chunks_anchored(data, SMALL, lane_multiple=8):
            assert dg == hashlib.sha256(
                data[o:o + ln].tobytes()).hexdigest()


def _dense_byte() -> int:
    """A uniform byte value whose 64-byte block is a Gear candidate under
    SMALL.chunk — filling a stream with it forces a cut every min_blocks,
    ~avg/min times the provisioned expectation."""
    from dfs_tpu.ops.cdc_v2 import candidates_np

    return next(v for v in range(256)
                if candidates_np(np.full(64, v, np.uint8),
                                 SMALL.chunk).any())


def test_tight_capacity_overflow_redispatches(monkeypatch):
    """Cut capacity is provisioned for ~1.25x the EXPECTED count
    (cap_mode='tight'); content cutting at min_blocks everywhere must be
    detected (the device count is exact) and redone at the worst-case
    bound — byte-identical to the oracle, never silently truncated."""
    import dfs_tpu.ops.cdc_anchored as A

    data = np.full(100000, _dense_byte(), dtype=np.uint8)
    calls: list[str] = []
    orig = A.region_dispatch

    def spy(*a, **kw):
        calls.append(kw.get("cap_mode", "tight"))
        return orig(*a, **kw)

    monkeypatch.setattr(A, "region_dispatch", spy)
    got = batch_chunks_anchored(data, SMALL, lane_multiple=8)
    assert "full" in calls, "dense content never hit the retry path"
    assert got == chunk_file_anchored_np(data, SMALL)


def test_tight_capacity_overflow_in_region_walk(monkeypatch):
    """Same retry through the pipelined multi-window walk (the fragmenter
    collect path), where the device carry chained past the overflowing
    window must stay valid."""
    import dfs_tpu.fragmenter.cdc_anchored as F

    data = np.full(200000, _dense_byte(), dtype=np.uint8).tobytes()
    calls: list[str] = []
    orig = F.region_chunks

    def spy(*a, **kw):
        calls.append(kw.get("cap_mode", "tight"))
        return orig(*a, **kw)

    monkeypatch.setattr(F, "region_chunks", spy)
    # 64 KiB windows: at SMALL's geometry the dense cut count per window
    # (stride/min_bytes) clears the tight bound; 16 KiB windows would not
    got = anchored_frag(region_bytes=65536).chunk(data)
    assert "full" in calls, "walk never hit the collect-retry path"
    arr = np.frombuffer(data, np.uint8)
    assert [(c.offset, c.length, c.digest) for c in got] == \
        chunk_file_anchored_np(arr, SMALL)


# ----------------------------------------------------------- fragmenters --

def anchored_frag(**kw):
    from dfs_tpu.fragmenter.cdc_anchored import AnchoredTpuFragmenter

    kw.setdefault("region_bytes", 16384)
    return AnchoredTpuFragmenter(SMALL, cpu_cutoff=0, lane_multiple=8, **kw)


def test_fragmenter_matches_oracle_and_cpu():
    from dfs_tpu.fragmenter.cdc_anchored import AnchoredCpuFragmenter

    data = corpus(100000, seed=40).tobytes()
    tpu = anchored_frag().chunk(data)
    cpu = AnchoredCpuFragmenter(SMALL).chunk(data)
    assert tpu == cpu
    assert sum(c.length for c in tpu) == len(data)


def test_region_walk_transparent():
    # region_bytes small forces many carries; result must equal one-shot
    data = corpus(120000, seed=41).tobytes()
    big = anchored_frag(region_bytes=1 << 30)
    small = anchored_frag()
    assert big.chunk(data) == small.chunk(data)


def test_three_way_region_streaming_equality():
    """Large-region one-shot == tiny-region walk == streaming, and all
    equal the NumPy whole-stream oracle — the transparency property the
    region/carry design exists to guarantee."""
    arr = corpus(200000, seed=43)
    data = arr.tobytes()
    want = [(o, ln, dg) for o, ln, dg in chunk_file_anchored_np(arr, SMALL)]

    one_shot = anchored_frag(region_bytes=1 << 30).chunk(data)
    tiny_frag = anchored_frag()            # 16 KiB regions: many carries
    tiny = tiny_frag.chunk(data)
    blocks = [data[i:i + 7333] for i in range(0, len(data), 7333)]
    streamed = tiny_frag.manifest_stream(blocks, name="f").chunks

    for got in (one_shot, tiny, list(streamed)):
        assert [(c.offset, c.length, c.digest) for c in got] == want


def test_streaming_matches_chunk_any_blocking():
    data = corpus(90000, seed=42).tobytes()
    frag = anchored_frag()
    want = frag.manifest(data, name="f")
    for bs in (1000, 8192, 30000):
        stored = {}
        blocks = [data[i:i + bs] for i in range(0, len(data), bs)]
        got = frag.manifest_stream(
            blocks, name="f", store=lambda dg, b: stored.setdefault(dg, b))
        assert got.chunks == want.chunks
        assert got.file_id == want.file_id
        assert b"".join(stored[c.digest] for c in got.chunks) == data


def test_streaming_block_lands_exactly_on_window_end():
    """A block boundary that lands exactly on a window end mid-stream must
    NOT finalize the walk early (the tail segment carries on): regression
    for inferring `final` from end == bytes-received-so-far."""
    frag = anchored_frag()             # region_bytes=16384
    data = corpus(50000, seed=44).tobytes()
    # first block = exactly one region; the dispatcher sees n_known ==
    # base + region_bytes with more data still to come
    blocks = [data[:16384], data[16384:]]
    got = frag.manifest_stream(blocks, name="f").chunks
    want = anchored_frag().chunk(data)
    assert list(got) == want


def test_factory_anchored_kinds():
    from dfs_tpu.fragmenter.base import get_fragmenter

    assert get_fragmenter("cdc-anchored").name == "cdc-anchored"
    assert get_fragmenter("cdc-anchored-tpu").name == "cdc-anchored-tpu"


def test_factory_auto_resolves_by_device(monkeypatch):
    """'auto' (the serve default) must pick the anchored TPU pipeline on
    TPU hosts and the anchored CPU oracle elsewhere."""
    import dfs_tpu.fragmenter.base as base

    monkeypatch.setattr(base, "tpu_available", lambda: True)
    assert base.get_fragmenter("auto").name == "cdc-anchored-tpu"
    monkeypatch.setattr(base, "tpu_available", lambda: False)
    assert base.get_fragmenter("auto").name == "cdc-anchored"


def test_factory_auto_honors_chunk_params(monkeypatch):
    """Operator chunk sizing flows through auto into the nested grid
    (ADVICE round 1: the anchored branch silently dropped CDCParams)."""
    import dfs_tpu.fragmenter.base as base
    from dfs_tpu.config import CDCParams

    monkeypatch.setattr(base, "tpu_available", lambda: False)
    f = base.get_fragmenter(
        "auto", cdc_params=CDCParams(min_size=1024, avg_size=4096,
                                     max_size=32768))
    assert f.params.chunk.min_blocks == 16
    assert f.params.chunk.avg_blocks == 64
    assert f.params.chunk.max_blocks == 512
    assert f.params.seg_max == f.params.chunk.strip_blocks * 64


def test_cdc_tpu_v1_deprecation_warning():
    import warnings

    from dfs_tpu.fragmenter.base import get_fragmenter

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        get_fragmenter("cdc-tpu")
    assert any(issubclass(x.category, DeprecationWarning) for x in w)


# ---------------------------------------------------------------------------
# Pallas repack kernel (ops.repack) vs the XLA fallback
# ---------------------------------------------------------------------------

def test_repack_pallas_matches_xla_fallback():
    """The DMA-gather + in-register-rotate kernel must agree with
    vmap(dynamic_slice)+funnel bit-for-bit, including the clamp branch
    (a segment start within one DMA window of the buffer end) and every
    byte phase. Runs through the Pallas interpreter on CPU; on real TPU
    the same kernel is exercised end-to-end by bench.py's hashlib
    asserts."""
    import jax
    import numpy as np

    from dfs_tpu.ops.repack import (_window_rows, repack_lanes,
                                    repack_lanes_xla)

    lane_words = 1024                      # 8 rows per lane
    m_total = 8 * 1024                     # multiple of the 1024-word tiling
    assert m_total // 128 >= _window_rows(lane_words)
    rng = np.random.default_rng(7)
    words = jax.device_put(
        rng.integers(0, 2**32, size=m_total, dtype=np.uint32))

    hi = m_total - lane_words - 1          # caller invariant bound
    offs = [0, 1, 5, 1023, 1024, 1025, hi, hi - 1, hi - 1023]
    offs += [int(x) for x in rng.integers(0, hi + 1, size=7)]
    w_off = np.asarray(offs, dtype=np.int32)
    sh8 = np.asarray([(i % 4) * 8 for i in range(len(offs))], np.uint32)

    want = np.asarray(repack_lanes_xla(words, jax.device_put(w_off),
                                       jax.device_put(sh8), lane_words))
    got = np.asarray(repack_lanes(words, jax.device_put(w_off),
                                  jax.device_put(sh8), lane_words,
                                  interpret=True))
    assert np.array_equal(got, want)


def test_region_buffer_size_is_dma_tiled():
    """The staging buffer must land on the repack kernel's 4096-byte DMA
    tiling, and region_dispatch's floored m_words recovery must keep the
    chunk output identical to the oracle (covered by the oracle-parity
    tests above running through region_chunks)."""
    from dfs_tpu.ops.cdc_anchored import (AnchoredCdcParams,
                                          region_buffer_size)

    p = AnchoredCdcParams()
    for n in (1, 4096, 64 * 2**20, 64 * 2**20 - 5):
        assert region_buffer_size(n, p) % 4096 == 0


def test_factory_auto_reprobes_and_flips_both_ways(monkeypatch):
    """'auto' must not pin the boot-time engine forever: the shared
    harness link swings ~1.5 GB/s <-> ~10 MB/s hour to hour (round-3
    finding), so the wrapper re-probes and flips engines in BOTH
    directions, logging the flip."""
    import logging

    import dfs_tpu.fragmenter.base as base

    link_ok = {"v": False}
    monkeypatch.setattr(base, "tpu_available", lambda: link_ok["v"])
    f = base.get_fragmenter("auto")
    assert f.name == "cdc-anchored"

    # link comes good -> flip up
    link_ok["v"] = True
    with_caplog = []
    handler = logging.Handler()
    handler.emit = lambda rec: with_caplog.append(rec.getMessage())
    logging.getLogger("dfs_tpu.fragmenter").addHandler(handler)
    try:
        f.reprobe_now()
        assert f.name == "cdc-anchored-tpu"
        # link collapses -> flip back down
        link_ok["v"] = False
        f.reprobe_now()
        assert f.name == "cdc-anchored"
        assert sum("auto engine flip" in m for m in with_caplog) == 2
    finally:
        logging.getLogger("dfs_tpu.fragmenter").removeHandler(handler)
    # chunking still works across flips (same params, same boundaries)
    data = b"x" * 300_000
    assert [c.digest for c in f.chunk(data)] \
        == [c.digest for c in base.get_fragmenter("cdc-anchored").chunk(data)]


def test_factory_auto_background_reprobe_is_throttled(monkeypatch):
    """Data-plane calls trigger at most one background probe per
    interval; an elapsed interval flips the engine without blocking the
    caller."""
    import time

    import dfs_tpu.fragmenter.base as base

    calls = {"n": 0}

    def probe():
        calls["n"] += 1
        return calls["n"] > 1       # first probe: CPU; later: TPU

    f = base.AutoAnchoredFragmenter(
        base._anchored_params(None), probe=probe, reprobe_s=0.0)
    assert f.name == "cdc-anchored" and calls["n"] == 1
    f.chunk(b"y" * 200_000)          # kicks a background re-probe
    for _ in range(100):
        if f.name == "cdc-anchored-tpu":
            break
        time.sleep(0.05)
    assert f.name == "cdc-anchored-tpu"
    assert calls["n"] == 2


def test_tight_segment_lane_overflow_redispatches(monkeypatch):
    """Segment LANES are provisioned at ~1.1x the expected count
    (cap_mode='tight', _tight_segment_lanes); a region with more
    segments than that must trip the exact on-device bound count and
    redo at the worst-case bound — byte-identical to the oracle, never
    a silently truncated chunk table."""
    import dfs_tpu.ops.cdc_anchored as A

    # force the tight provisioning far below the real segment count so
    # ORDINARY content overflows the lanes (the select scan fills every
    # slot); the full-bound redispatch must recover exactly
    monkeypatch.setattr(A, "_tight_segment_lanes",
                        lambda params, m_words, lane_multiple: 8)
    A.make_chain_fn.cache_clear()
    try:
        rng = np.random.default_rng(3)
        data = rng.integers(0, 256, size=100000, dtype=np.uint8)
        calls: list[str] = []
        orig = A.region_dispatch

        def spy(*a, **kw):
            calls.append(kw.get("cap_mode", "tight"))
            return orig(*a, **kw)

        monkeypatch.setattr(A, "region_dispatch", spy)
        got = batch_chunks_anchored(data, SMALL, lane_multiple=8)
        assert "full" in calls, "lane overflow never hit the retry path"
        assert got == chunk_file_anchored_np(data, SMALL)
    finally:
        A.make_chain_fn.cache_clear()


def test_tight_segment_lane_overflow_in_pipelined_walk(monkeypatch):
    """Lane overflow through the MULTI-WINDOW pipelined walk: window k's
    lane tables truncate, but its device carry (from the full-bound
    select scan) stays exact, so the windows already dispatched on that
    carry remain valid and only window k redoes at 'full'. The walk must
    produce the oracle chunk table with no discontinuity."""
    import dfs_tpu.fragmenter.cdc_anchored as F
    import dfs_tpu.ops.cdc_anchored as A

    monkeypatch.setattr(A, "_tight_segment_lanes",
                        lambda params, m_words, lane_multiple: 8)
    A.make_chain_fn.cache_clear()
    try:
        rng = np.random.default_rng(5)
        data = rng.integers(0, 256, size=200000, dtype=np.uint8).tobytes()
        calls: list[str] = []
        orig = F.region_chunks

        def spy(*a, **kw):
            calls.append(kw.get("cap_mode", "tight"))
            return orig(*a, **kw)

        monkeypatch.setattr(F, "region_chunks", spy)
        got = anchored_frag(region_bytes=65536, max_inflight=3).chunk(data)
        assert "full" in calls, "walk never hit the lane-overflow retry"
        arr = np.frombuffer(data, np.uint8)
        assert [(c.offset, c.length, c.digest) for c in got] == \
            chunk_file_anchored_np(arr, SMALL)
    finally:
        A.make_chain_fn.cache_clear()


def _random_two_plane_tiles(rng, m_tiles, density=2):
    """Random pass-A-shaped [2, m_tiles] tile planes: ~1/density tiles
    hold a first anchor, about half of those also a second (strictly
    larger, same tile) — mirrors make_anchor_fn's output invariants."""
    tiles = np.full((2, m_tiles), 2**30, np.int32)
    k = max(1, m_tiles // density)
    idx = rng.choice(m_tiles, size=k, replace=False)
    off1 = rng.integers(0, TILE_BYTES - 1, size=k)   # <= TILE_BYTES - 2
    tiles[0, idx] = (idx * TILE_BYTES + off1).astype(np.int32)
    has2 = rng.random(k) < 0.5
    off2 = off1 + 1 + rng.integers(0, TILE_BYTES - 1 - off1)
    tiles[1, idx[has2]] = (idx[has2] * TILE_BYTES
                           + off2[has2]).astype(np.int32)
    return tiles


def test_pallas_select_matches_xla_scan():
    """The on-core Pallas selection walk (ops.select_pallas) must agree
    with the XLA scan bit-for-bit: random anchor-tile patterns, final
    and non-final regions, zero and carried start0. Interpret mode on
    CPU; on real TPU the same kernel is exercised end-to-end by
    bench.py's hashlib gates (make_chain_fn picks it there)."""
    import jax.numpy as jnp

    from dfs_tpu.ops.select_pallas import make_select_fn_pallas

    rng = np.random.default_rng(11)
    params = SMALL
    for trial in range(2):
        n = int(rng.integers(20000, 120000))
        m_tiles = 1 << (-(-n // TILE_BYTES) - 1).bit_length()
        cap = m_tiles * TILE_BYTES // params.seg_min + 1
        tiles = _random_two_plane_tiles(rng, m_tiles)
        import dfs_tpu.ops.cdc_anchored as A
        for final in (True, False):
            for start0 in (0, 1234):
                ref = A.make_select_fn(params, m_tiles, cap)(
                    jnp.asarray(tiles), jnp.int32(start0), jnp.int32(n),
                    jnp.bool_(final))
                got = make_select_fn_pallas(
                    params, m_tiles, cap, interpret=True)(
                    jnp.asarray(tiles), jnp.int32(start0), jnp.int32(n),
                    jnp.bool_(final))
                np.testing.assert_array_equal(
                    np.asarray(ref), np.asarray(got))


def test_pallas_select_large_region_block_addressing():
    """Production-shaped geometry (96K/128K segments, 4 MiB region):
    t0 crosses the 1024-entry block boundary many times, so the kernel's
    8-row-aligned dynamic block read and (row + r0)*128 + col global
    index arithmetic are actually exercised (the small-n test's windows
    all start in block zero)."""
    import jax.numpy as jnp

    import dfs_tpu.ops.cdc_anchored as A
    from dfs_tpu.ops.select_pallas import make_select_fn_pallas

    params = AnchoredCdcParams()        # production segment geometry
    n = 4 * 2**20
    m_tiles = n // TILE_BYTES           # 8192 tiles -> t0 up to ~8192
    cap = n // params.seg_min + 1
    rng = np.random.default_rng(12)
    tiles = _random_two_plane_tiles(rng, m_tiles, density=16)
    for final in (True, False):
        ref = A.make_select_fn(params, m_tiles, cap)(
            jnp.asarray(tiles), jnp.int32(0), jnp.int32(n),
            jnp.bool_(final))
        got = make_select_fn_pallas(params, m_tiles, cap,
                                    interpret=True)(
            jnp.asarray(tiles), jnp.int32(0), jnp.int32(n),
            jnp.bool_(final))
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
