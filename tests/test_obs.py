"""Observability tests (dfs_tpu/obs): trace-context propagation across
the peer wire, cluster trace stitching, Prometheus exposition, and the
pre-r09 compatibility guarantees (optional wire field, JSON /metrics
superset).

Cluster scaffolding mirrors test_node_cluster: real asyncio node pairs
on localhost ports, CPU CDC engine, and NO sleeps — every assertion
rides on awaited completions."""

import asyncio
import json
import re
import socket
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from dfs_tpu.comm.wire import read_msg, send_msg
from dfs_tpu.config import (CDCParams, ClusterConfig, NodeConfig,
                            ObsConfig, PeerAddr)
from dfs_tpu.node.runtime import StorageNodeServer
from dfs_tpu.obs import (Observability, RpcStats, new_span_id,
                         new_trace_id, parse_http_trace, parse_wire_trace)
from dfs_tpu.obs.stitch import merge_spans, render_tree
from dfs_tpu.serve.admission import AdmissionGate

CDC = CDCParams(min_size=64, avg_size=256, max_size=1024)


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def make_cluster_cfg(n: int, rf: int = 2) -> ClusterConfig:
    ports = _free_ports(2 * n)
    peers = tuple(
        PeerAddr(node_id=i + 1, host="127.0.0.1",
                 port=ports[2 * i], internal_port=ports[2 * i + 1])
        for i in range(n))
    return ClusterConfig(peers=peers, replication_factor=rf)


async def start_nodes(cluster, root: Path, **cfg_kw):
    nodes = {}
    cfg_kw.setdefault("cdc", CDC)
    cfg_kw.setdefault("health_probe_s", 0)
    for p in cluster.peers:
        cfg = NodeConfig(node_id=p.node_id, cluster=cluster,
                         data_root=root, fragmenter="cdc", **cfg_kw)
        node = StorageNodeServer(cfg)
        await node.start()
        nodes[p.node_id] = node
    return nodes


async def stop_nodes(nodes) -> None:
    for n in nodes.values():
        await n.stop()


def _req(port: int, method: str, path: str, body=None, headers=None):
    r = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                               data=body, method=method,
                               headers=headers or {})
    with urllib.request.urlopen(r, timeout=60) as resp:
        return resp.read()


# --------------------------------------------------------------------- #
# a minimal Prometheus text-format (0.0.4) parser — the in-repo checker
# the prom endpoint is validated against
# --------------------------------------------------------------------- #

_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$")
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prom(text: str):
    """-> (samples, types): samples maps (metric name, sorted label
    tuple) -> float; types maps family -> declared type. Raises
    AssertionError on any malformed line, on a family declared twice,
    or on a family whose samples are not CONTIGUOUS (the exposition
    format's grouping rule — strict parsers reject interleaving)."""
    samples, types = {}, {}
    done_families, cur_family = set(), None

    def family(name: str) -> str:
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in types:
                return name[:-len(suffix)]
        return name

    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split()
            assert len(parts) >= 4 and parts[1] in ("TYPE", "HELP"), line
            if parts[1] == "TYPE":
                assert parts[2] not in types, \
                    f"family {parts[2]} declared twice"
                types[parts[2]] = parts[3]
            continue
        m = _SAMPLE.match(line)
        assert m, f"malformed prom sample line: {line!r}"
        name, labels, value = m.groups()
        fam = family(name)
        if fam != cur_family:
            assert fam not in done_families, \
                f"family {fam} samples not contiguous"
            if cur_family is not None:
                done_families.add(cur_family)
            cur_family = fam
        lbl = tuple(sorted(_LABEL.findall(labels))) if labels else ()
        if labels:
            # the label block must be FULLY consumed by well-formed pairs
            stripped = _LABEL.sub("", labels).replace(",", "")
            assert stripped == "", f"bad labels in {line!r}"
        v = float("inf") if value == "+Inf" else float(value)
        key = (name, lbl)
        assert key not in samples, f"duplicate sample {key}"
        samples[key] = v
    return samples, types


# --------------------------------------------------------------------- #
# unit: ids, carriers, span nesting, ring bounds
# --------------------------------------------------------------------- #

def test_parse_http_trace():
    tid, sid = new_trace_id(), new_span_id()
    assert parse_http_trace(f"{tid}-{sid}") == (tid, sid)
    assert parse_http_trace(None) is None
    assert parse_http_trace("") is None
    assert parse_http_trace("nonsense") is None
    assert parse_http_trace(f"{tid}-short") is None
    assert parse_http_trace(f"{tid[:-1]}g-{sid}") is None  # non-hex


def test_is_id_rejects_int_parse_lookalikes():
    """int(s, 16) accepts '0x'/sign/underscore/uppercase forms — the
    strict charset must not (ids are canonical lowercase hex)."""
    from dfs_tpu.obs import TRACE_HEX, is_id

    good = new_trace_id()
    assert is_id(good, TRACE_HEX)
    for bad in ("0x" + good[2:], "+" + good[1:], "-" + good[1:],
                good[:-2] + "_a", good.upper(), " " + good[1:]):
        assert len(bad) == TRACE_HEX
        assert not is_id(bad, TRACE_HEX), bad


def test_parse_wire_trace():
    tid, sid = new_trace_id(), new_span_id()
    assert parse_wire_trace({"t": tid, "s": sid, "f": 3}) == (tid, sid, 3)
    assert parse_wire_trace({"t": tid, "s": sid}) == (tid, sid, None)
    # malformed shapes degrade to None, never raise (old/hostile peers)
    for bad in (None, "x", 7, [], {"t": tid}, {"t": 1, "s": 2},
                {"t": tid, "s": sid, "f": True}):
        got = parse_wire_trace(bad)
        assert got is None or got[2] is None


def test_span_nesting_records_parent_chain():
    obs = Observability(ObsConfig(trace_ring=64), node_id=7)

    async def run():
        with obs.request_span("http./x") as root:
            assert root is not None
            with obs.span("inner", peer=2) as sp:
                sp.bytes = 123

    asyncio.run(run())
    # both spans share one trace; inner's parent is the request span
    ring = obs._ring
    assert len(ring) == 2
    inner, outer = ring[0], ring[1]   # inner finishes first
    assert inner[0] == outer[0]               # same trace id
    assert inner[2] == outer[1]               # parent linkage
    assert outer[2] is None                   # fresh root
    spans = obs.spans_for(inner[0])
    assert {s["name"] for s in spans} == {"http./x", "inner"}
    assert next(s for s in spans if s["name"] == "inner")["bytes"] == 123


def test_tracing_off_is_noop_but_latency_survives():
    obs = Observability(ObsConfig(trace_ring=0), node_id=1)
    with obs.request_span("http./x"):
        with obs.span("phase", latency=True):
            pass
        assert obs.wire_trace() is None
    assert obs.spans_for("0" * 32) == []
    assert "phase" in obs.latency.snapshot()   # metrics stay on
    assert obs.stats()["traceRing"] == 0


def test_span_error_annotation():
    obs = Observability(ObsConfig(trace_ring=8), node_id=1)
    with pytest.raises(ValueError):
        with obs.request_span("http./x"):
            with obs.span("boom"):
                raise ValueError("nope")
    tid = obs._ring[0][0]
    spans = obs.spans_for(tid)
    assert next(s for s in spans if s["name"] == "boom")["err"] \
        == "ValueError"


def test_ring_is_bounded():
    obs = Observability(ObsConfig(trace_ring=4), node_id=1)
    for _ in range(10):
        with obs.request_span("http./x"):
            pass
    assert len(obs._ring) == 4


def test_rpcstats_cardinality_cap():
    st = RpcStats()
    for i in range(RpcStats._MAX_KEYS + 50):
        st.record(i, "op", 0.001)
    snap = st.snapshot()
    assert len(snap) <= RpcStats._MAX_KEYS + 1
    assert snap["_overflow:_overflow"]["count"] == 50


def test_admission_queue_wait_records_span():
    obs = Observability(ObsConfig(trace_ring=32), node_id=1)
    gate = AdmissionGate("download", slots=1, queue_depth=4, obs=obs)

    async def run():
        await gate.acquire()          # takes the slot

        async def queued():
            with obs.request_span("http./download"):
                await gate.acquire()
            gate.release()

        t = asyncio.create_task(queued())
        while not gate._queue:        # deterministic: just yield until
            await asyncio.sleep(0)    # the waiter parked (no timed sleep)
        gate.release()                # slot transfers to the waiter
        await t

    asyncio.run(run())
    names = [r[3] for r in obs._ring]
    assert "admission.download.wait" in names


# --------------------------------------------------------------------- #
# stitcher
# --------------------------------------------------------------------- #

def test_merge_spans_dedups():
    a = {"node": 1, "s": "aa", "t": "t", "name": "x", "t0": 0.0, "d": 1.0}
    b = {"node": 2, "s": "aa", "t": "t", "name": "y", "t0": 0.0, "d": 1.0}
    assert len(merge_spans([[a], [a, b]])) == 2


def test_render_tree_structure_and_slow_log():
    tid = "f" * 32
    spans = [
        {"t": tid, "s": "a" * 16, "p": None, "name": "http./download",
         "node": 1, "t0": 0.0, "d": 2.5},
        {"t": tid, "s": "b" * 16, "p": "a" * 16, "name": "rpc.get_chunks",
         "node": 1, "peer": 2, "t0": 0.1, "d": 0.2, "bytes": 2048},
        {"t": tid, "s": "c" * 16, "p": "b" * 16, "name": "peer.get_chunks",
         "node": 2, "t0": 0.15, "d": 0.1},
        # orphan (parent evicted): must surface as a top-level node
        {"t": tid, "s": "d" * 16, "p": "e" * 16, "name": "cas.get",
         "node": 3, "t0": 0.2, "d": 0.05},
    ]
    out = render_tree(spans, slow_s=1.0)
    assert "slow spans (>= 1s):" in out
    assert out.count("http./download") == 2     # slow log + tree
    # the child nests under its parent, cross-node
    tree_lines = out.splitlines()
    rpc_line = next(ln for ln in tree_lines if "rpc.get_chunks" in ln)
    peer_line = next(ln for ln in tree_lines if "peer.get_chunks" in ln)
    assert len(peer_line) - len(peer_line.lstrip("│ ├└─")) >= 0
    assert tree_lines.index(peer_line) == tree_lines.index(rpc_line) + 1
    assert "cas.get" in out                     # orphan not silenced
    assert "2.0KiB" in out
    assert render_tree([], 1.0).startswith("(no spans")


# --------------------------------------------------------------------- #
# cluster: stitched cross-node trace (the acceptance scenario)
# --------------------------------------------------------------------- #

def test_cluster_stitched_trace(tmp_path, rng):
    """3-node upload+download tagged with one client trace id: the
    cluster stitch must return a single trace whose parent ids link
    client-facing HTTP spans to the peer RPC spans they caused, across
    node boundaries."""
    data = rng.integers(0, 256, size=60_000, dtype=np.uint8).tobytes()
    tid = new_trace_id()
    hdr = {"X-Dfs-Trace": f"{tid}-{new_span_id()}"}

    async def run():
        cluster = make_cluster_cfg(3)
        nodes = await start_nodes(cluster, tmp_path)
        try:
            p = cluster.peers
            up = json.loads(await asyncio.to_thread(
                _req, p[0].port, "POST", "/upload?name=t.bin", data, hdr))
            got = await asyncio.to_thread(
                _req, p[2].port, "GET",
                f"/download?fileId={up['fileId']}", None, hdr)
            assert got == data
            return json.loads((await asyncio.to_thread(
                _req, p[0].port, "GET",
                f"/trace?traceId={tid}")).decode())
        finally:
            await stop_nodes(nodes)

    trace = asyncio.run(run())
    spans = trace["spans"]
    assert all(s["t"] == tid for s in spans)
    by_id = {s["s"]: s for s in spans}
    nodes_seen = {s["node"] for s in spans}
    assert len(nodes_seen) >= 2
    names = {s["name"] for s in spans}
    # client-facing HTTP spans on the nodes the client actually hit
    up_span = next(s for s in spans if s["name"] == "http./upload")
    down_span = next(s for s in spans if s["name"] == "http./download")
    assert up_span["node"] == 1 and down_span["node"] == 3
    # the HTTP spans CAUSED rpc spans: rpc.* parents chain up to them
    def chains_to(span, ancestor_id):
        while span is not None:
            if span["s"] == ancestor_id:
                return True
            span = by_id.get(span["p"])
        return False

    rpc_from_upload = [s for s in spans if s["name"].startswith("rpc.")
                       and chains_to(s, up_span["s"])]
    assert rpc_from_upload, "upload produced no rpc spans"
    # cross-node parent links: a peer.* span whose parent span lives on
    # a DIFFERENT node (the rpc client span that caused it)
    cross = [s for s in spans
             if s.get("p") in by_id
             and by_id[s["p"]]["node"] != s["node"]]
    assert cross, "no cross-node parent links"
    assert any(s["name"].startswith("peer.") for s in cross)
    # context propagated through create_task + the CAS executor awaits
    assert any(n.startswith("cas.") for n in names)
    # the stitcher renders it as ONE tree (single header line, every
    # span present)
    rendered = render_tree(spans, slow_s=trace["slowSpanS"])
    assert rendered.splitlines()[0].startswith(f"trace {tid}")
    assert "http./upload" in rendered and "http./download" in rendered
    assert "peer.store_chunks" in rendered


def test_trace_endpoint_validates_id(tmp_path):
    async def run():
        cluster = make_cluster_cfg(1, rf=1)
        nodes = await start_nodes(cluster, tmp_path)
        try:
            port = cluster.peers[0].port
            with pytest.raises(urllib.error.HTTPError) as ei:
                await asyncio.to_thread(
                    _req, port, "GET", "/trace?traceId=nothex")
            assert ei.value.code == 400
            ei.value.read()
            # valid-but-unknown id: empty span list, not an error
            out = json.loads((await asyncio.to_thread(
                _req, port, "GET",
                f"/trace?traceId={'0' * 32}&cluster=0")).decode())
            assert out["spans"] == []
        finally:
            await stop_nodes(nodes)

    asyncio.run(run())


# --------------------------------------------------------------------- #
# Prometheus exposition + JSON backward compatibility
# --------------------------------------------------------------------- #

# top-level JSON /metrics keys of the r08 schema — the default output
# must remain a superset (pre-r09 scrapers keep working untouched)
R08_METRICS_KEYS = {"nodeId", "underReplicated", "latency", "peersAlive",
                    "serve", "ingest"}


def test_prom_exposition_and_json_superset(tmp_path, rng):
    data = rng.integers(0, 256, size=40_000, dtype=np.uint8).tobytes()

    async def run():
        cluster = make_cluster_cfg(3)
        nodes = await start_nodes(cluster, tmp_path)
        try:
            p = cluster.peers
            up = json.loads(await asyncio.to_thread(
                _req, p[0].port, "POST", "/upload?name=m.bin", data))
            await asyncio.to_thread(
                _req, p[0].port, "GET", f"/download?fileId={up['fileId']}")
            prom = (await asyncio.to_thread(
                _req, p[0].port, "GET", "/metrics?format=prom")).decode()
            # server-side RPC series live on the RECEIVING nodes
            prom2 = (await asyncio.to_thread(
                _req, p[1].port, "GET", "/metrics?format=prom")).decode()
            js = json.loads((await asyncio.to_thread(
                _req, p[0].port, "GET", "/metrics")).decode())
            return prom, prom2, js
        finally:
            await stop_nodes(nodes)

    prom, prom2, js = asyncio.run(run())
    samples, types = parse_prom(prom)
    samples2, _ = parse_prom(prom2)

    # counters made it over
    assert samples[("dfs_counter_total", (("name", "uploads"),))] == 1.0
    assert types["dfs_counter_total"] == "counter"

    # RPC per-peer per-op client series exist for real peers
    rpc_ops = {lbls for (name, lbls) in samples
               if name == "dfs_rpc_client_ops_total"}
    assert (("op", "store_chunks"), ("peer", "2")) in rpc_ops \
        or (("op", "store_chunks"), ("peer", "3")) in rpc_ops
    server_ops = {dict(lbls)["op"] for (name, lbls) in samples2
                  if name == "dfs_rpc_server_ops_total"}
    assert "store_chunks" in server_ops or "has_chunks" in server_ops

    # latency histograms: real log2 buckets, cumulative, +Inf == count
    hist_names = {dict(lbls)["name"]
                  for (name, lbls) in samples
                  if name == "dfs_latency_seconds_bucket"}
    assert "http.request" in hist_names
    for hname in hist_names:
        buckets = sorted(
            (float(dict(lbls)["le"]), v)
            for (name, lbls), v in samples.items()
            if name == "dfs_latency_seconds_bucket"
            and dict(lbls)["name"] == hname)
        counts = [v for _, v in buckets]
        assert counts == sorted(counts), f"{hname} buckets not cumulative"
        count = samples[("dfs_latency_seconds_count",
                         (("name", hname),))]
        assert buckets[-1][0] == float("inf")
        assert buckets[-1][1] == count

    # default JSON output: strict superset of the r08 schema
    assert R08_METRICS_KEYS <= set(js)
    assert "obs" in js and js["obs"]["traceRing"] == 2048
    assert "rpcClient" in js["obs"]


# --------------------------------------------------------------------- #
# pre-r09 wire compatibility
# --------------------------------------------------------------------- #

def test_old_peer_without_trace_field_interops(tmp_path, rng):
    """A tracing node must interoperate byte-identically with a peer
    whose client never sends the wire ``trace`` field (pre-r09 node):
    upload driven by the OLD-style node, download served by the tracing
    node, plus raw frames with absent/garbage trace fields."""
    data = rng.integers(0, 256, size=50_000, dtype=np.uint8).tobytes()

    async def run():
        cluster = make_cluster_cfg(2)
        nodes = await start_nodes(cluster, tmp_path)
        try:
            # node 2 becomes the pre-r09 node: its client has no obs
            # hook, so its frames carry NO trace field — exactly the
            # old wire format
            nodes[2].client._obs = None
            m, _ = await nodes[2].upload(data, "compat.bin")
            _, got = await nodes[1].download(m.file_id)
            assert got == data

            # raw frame WITHOUT a trace field against the tracing node
            addr = cluster.peers[0]
            reader, writer = await asyncio.open_connection(
                addr.host, addr.internal_port)
            try:
                await send_msg(writer, {"op": "has_chunks",
                                        "digests": []})
                resp, _ = await read_msg(reader)
                assert resp["ok"] is True
                # garbage trace field: ignored, never an error
                await send_msg(writer, {"op": "health",
                                        "trace": "garbage"})
                resp, _ = await read_msg(reader)
                assert resp["ok"] is True and resp["nodeId"] == 1
            finally:
                writer.close()
                await writer.wait_closed()
            ring_names = {r[3] for r in nodes[1].obs._ring}
            return nodes[1].obs.rpc_server.snapshot(), ring_names
        finally:
            await stop_nodes(nodes)

    server_rpc, ring_names = asyncio.run(run())
    # the tracing node's server table recorded the old peer's calls
    # under the unknown-sender label
    assert any(k.startswith("-:") for k in server_rpc)
    # untraced HEAVY ops still root a trace (diagnosable), but untraced
    # cheap ops (health/has_chunks probes) must NOT mint ring entries —
    # probe noise would evict client-tagged spans
    assert "peer.store_chunks" in ring_names
    assert "peer.health" not in ring_names
    assert "peer.has_chunks" not in ring_names
