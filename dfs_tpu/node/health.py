"""Failure detection: health-checked peer registry (SURVEY.md §5.3).

The reference's failure detection is a manual ``GET /status`` from the client
menu (StorageNode.java:71-74) — nodes themselves never probe each other and
discover death only by timing out mid-request (2 s × 3 attempts per call,
:208-216). This monitor keeps a live/dead view per peer so the data path can
skip known-dead peers immediately (one cheap set lookup instead of burning
the full retry envelope on every chunk), while a low-rate probe loop notices
recovery and flips peers back to alive.
"""

from __future__ import annotations

import asyncio
import time

from dfs_tpu.comm.rpc import (InternalClient, RpcError, RpcUnreachable)
from dfs_tpu.config import ClusterConfig
from dfs_tpu.utils.aio import create_logged_task
from dfs_tpu.utils.logging import get_logger


class HealthMonitor:
    def __init__(self, cluster: ClusterConfig, self_id: int,
                 client: InternalClient,
                 probe_interval_s: float = 5.0, obs=None) -> None:
        self.cluster = cluster
        self.self_id = self_id
        self.client = client
        self.probe_interval_s = probe_interval_s
        self.log = get_logger("health", self_id)
        # observability hook: liveness TRANSITIONS are journaled
        # (peer_down/peer_up flight-recorder events) — the exact
        # lifecycle facts a post-mortem needs and the process forgets
        self._obs = obs
        # optimistic start: everyone alive (matches reference behavior of
        # always trying peers); flips on first failure
        self._alive: dict[int, bool] = {
            p.node_id: True for p in cluster.peers if p.node_id != self_id}
        self._last_change: dict[int, float] = {}
        self._task: asyncio.Task | None = None

    def is_alive(self, node_id: int) -> bool:
        return self._alive.get(node_id, True)

    def mark_dead(self, node_id: int) -> None:
        """Data-path feedback: a call to this peer just exhausted retries."""
        if self._alive.get(node_id):
            self._alive[node_id] = False
            self._last_change[node_id] = time.monotonic()
            self.log.warning("peer %d marked dead", node_id)
            if self._obs is not None:
                self._obs.event("peer_down", peer=node_id)

    def mark_alive(self, node_id: int) -> None:
        if not self._alive.get(node_id, True):
            self._alive[node_id] = True
            self._last_change[node_id] = time.monotonic()
            self.log.info("peer %d back alive", node_id)
            if self._obs is not None:
                self._obs.event("peer_up", peer=node_id)

    def snapshot(self) -> dict[str, bool]:
        return {str(k): v for k, v in sorted(self._alive.items())}

    async def probe_once(self) -> None:
        async def probe(peer) -> None:
            try:
                await self.client.health(peer)
                self.mark_alive(peer.node_id)
            except RpcUnreachable:
                self.mark_dead(peer.node_id)
            except RpcError as e:
                # an application-level error came from a peer that
                # ANSWERED: liveness evidence, not death — and it must
                # not escape, or the whole probe loop dies with it (the
                # pre-round-8 bug: one RpcRemoteError killed probing
                # for the life of the node, silently)
                self.mark_alive(peer.node_id)
                self.log.warning("health probe of node %d answered an "
                                 "error: %s", peer.node_id, e)

        await asyncio.gather(*(probe(p) for p in self.cluster.peers
                               if p.node_id != self.self_id))

    def start(self) -> None:
        async def loop() -> None:
            while True:
                await asyncio.sleep(self.probe_interval_s)
                await self.probe_once()

        # retained reference + logged death: an unexpected exception in
        # the probe loop must be visible, not vanish with a GC'd task
        self._task = create_logged_task(loop(), self.log, "health-probe")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
