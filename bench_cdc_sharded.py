"""Sharded ANCHORED streaming-CDC ingest benchmark -> CDC_SHARD_r15.json.

The flagship anchored pipeline's streaming region walk, sharded over
devices (fragmenter/cdc_anchored_sharded.py — ROADMAP item 5's last
data-plane gap). Two phases on one chart-ready schema:

1. **stream** — streamed anchored ingest GiB/s at 1/2/4 virtual devices
   (one fresh subprocess per count, ONE intra-op thread per device, the
   MULTICHIP_SCALE_r05.json / WIRE_r10.json methodology: the scaling
   claim is the DEVICE axis, not a hidden thread pool; wall-clock on a
   shared-host mesh is the honest number). Each arm drives the REAL
   ingest walk — ``ShardedAnchoredCdcFragmenter.chunks_stream`` with
   double-buffered host->device staging, sharded anchor pass A, host
   segment selection with the threaded carry, sharded boundary pass B,
   host SHA-NI hashing — over a multi-region random stream. The largest
   count also gates BYTE IDENTITY against the host engine
   (``AnchoredCpuFragmenter``): every span, every digest, and the
   stored-payload reconstruction.

2. **node** — the full ingest stack: a real 3-node in-process cluster
   (rf=2, windowed placement + bounded async CAS tier from r07, the
   zero-copy wire from r10) configured with ``fragmenter=cdc-anchored``
   + ``frag.devices`` — ``upload_stream`` chunks through the sharded
   walk, a DIFFERENT node serves the file back, and the bytes must
   round-trip exactly (file_id == sha256(body) is re-checked).

Acceptance (full mode): stream scaling at 4 devices >= 1.7x the
single-device streaming rate (the rolling strategy's r10 bar), byte
identity everywhere. ``--tiny`` is the tier-1 smoke (seconds): same
schema and machinery on a small geometry at 1-2 devices, identity gated,
perf reported but not gated (CI hosts stall unpredictably; the committed
artifact carries the perf claim). The tiny node phase swaps the
small-geometry fragmenter onto the node after construction — the
``NodeConfig.cdc`` surface pins anchored strips to the production
default, and compiling those shapes is the full run's job — while the
config->factory selection itself stays asserted on the node as built.

Usage: python bench_cdc_sharded.py [--tiny] [--out PATH]
(internal: --stream-worker N runs one mesh size in a fresh process)
"""

from __future__ import annotations

import os
import sys

# workers must configure XLA BEFORE any jax import (fresh process);
# the parent process needs >= 4 visible devices for the node phase
if "--stream-worker" in sys.argv:
    _n = int(sys.argv[sys.argv.index("--stream-worker") + 1])
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={_n} "
        "--xla_cpu_multi_thread_eigen=false "
        "intra_op_parallelism_threads=1 "
        + os.environ.get("XLA_FLAGS", ""))
elif "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=8 "
                               + os.environ.get("XLA_FLAGS", ""))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import argparse          # noqa: E402
import asyncio           # noqa: E402
import json              # noqa: E402
import socket            # noqa: E402
import subprocess        # noqa: E402
import time              # noqa: E402
from pathlib import Path  # noqa: E402

import numpy as np       # noqa: E402

ART = "CDC_SHARD_r15.json"

FULL = dict(devices=(1, 2, 4), region=8 * 2**20, total=96 * 2**20,
            repeats=3, node_devices=4, node_region=8 * 2**20,
            node_total=24 * 2**20, geometry="full")
TINY = dict(devices=(1, 2), region=16 * 1024, total=256 * 1024,
            repeats=2, node_devices=2, node_region=16 * 1024,
            node_total=192 * 1024, geometry="tiny")


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _params(geometry: str):
    from dfs_tpu.ops.cdc_anchored import AnchoredCdcParams

    if geometry == "full":
        return AnchoredCdcParams()       # production: 96-128 KiB segments
    from dfs_tpu.ops.cdc_v2 import AlignedCdcParams

    # tiny: the anchored_sharded_parity_check geometry — compiles in
    # seconds on the CI host, same code paths
    return AnchoredCdcParams(
        chunk=AlignedCdcParams(min_blocks=2, avg_blocks=4, max_blocks=16,
                               strip_blocks=64),
        seg_min=2048, seg_max=4096, seg_mask=2047)


def _blocks(data: bytes, n: int = 1 << 20):
    for off in range(0, len(data), n):
        yield data[off:off + n]


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


# ------------------------------------------------------------------ #
# phase 1 — streamed ingest scaling (fresh process per device count)
# ------------------------------------------------------------------ #

def stream_worker(n_dev: int, region: int, total: int, repeats: int,
                  geometry: str, check: bool) -> int:
    from dfs_tpu.config import FragmenterConfig
    from dfs_tpu.fragmenter.cdc_anchored import AnchoredCpuFragmenter
    from dfs_tpu.fragmenter.cdc_anchored_sharded import \
        ShardedAnchoredCdcFragmenter

    params = _params(geometry)
    frag = ShardedAnchoredCdcFragmenter(
        params, FragmenterConfig(devices=n_dev, region_bytes=region))
    rng = np.random.default_rng(29)
    data = rng.integers(0, 256, size=total, dtype=np.uint8).tobytes()

    def run_once() -> list:
        out = []
        for batch in frag.chunks_stream(_blocks(data)):
            out.extend(batch)
        return out

    chunks = run_once()                      # compile + warm pools
    if frag._unavailable:
        raise RuntimeError(f"sharded walk degraded at {n_dev} devices")
    frag.reset_staging_samples()             # scope the staging aggregate
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        chunks = run_once()
        best = min(best, time.perf_counter() - t0)
    rec = {"devices": n_dev, "region_bytes": region, "total_bytes": total,
           "seconds": round(best, 4),
           "gibps": round(total / best / 2**30, 4),
           "chunks": len(chunks),
           "staging_windows_timed": frag.staging_timed_windows()}
    bw = frag.staging_observed_bw()
    rec["staging_gibps"] = round(bw / 2**30, 4) if bw else None
    if check:
        # byte identity vs the host engine: spans, digests, AND stored
        # payload reconstruction through the store callback
        got: dict[str, bytes] = {}
        m = frag.manifest_stream(_blocks(data), name="bench",
                                 store=lambda d, b: got.setdefault(d, b))
        oracle = AnchoredCpuFragmenter(params, region_bytes=region) \
            .manifest_stream(_blocks(data), name="bench")
        same = [(c.offset, c.length, c.digest) for c in m.chunks] \
            == [(c.offset, c.length, c.digest) for c in oracle.chunks]
        rebuilt = b"".join(got[c.digest] for c in m.chunks) == data
        rec["identical"] = bool(same and m.file_id == oracle.file_id)
        rec["reconstruction_ok"] = bool(rebuilt)
        if not (rec["identical"] and rec["reconstruction_ok"]):
            raise AssertionError("sharded anchored output != host engine")
    print(json.dumps(rec))
    return 0


def stream_phase(p: dict) -> dict:
    out: dict = {"region_bytes": p["region"], "total_bytes": p["total"],
                 "methodology": ("virtual CPU mesh, one intra-op thread "
                                 "per device, fresh process per count "
                                 "(MULTICHIP_SCALE_r05.json scope: "
                                 "wall-clock, host-bound); streamed "
                                 "through the real ingest walk — "
                                 "staging, host select, device "
                                 "chunk+hash, emit. staging_gibps is "
                                 "the walk's self-measurement; the "
                                 "probe shares the device with compute "
                                 "(on a busy 1-device mesh it reads "
                                 "queue latency, not link speed)"),
                 "devices": [], "gibps": [], "staging_gibps": []}
    for n in p["devices"]:
        check = n == max(p["devices"])
        cmd = [sys.executable, __file__, "--stream-worker", str(n),
               "--region", str(p["region"]), "--total", str(p["total"]),
               "--repeats", str(p["repeats"]), "--geometry", p["geometry"]]
        if check:
            cmd.append("--check")
        log(f"  stream devices={n} (fresh process)…")
        res = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=1800)
        if res.returncode != 0:
            raise RuntimeError(f"stream worker failed:\n"
                               f"{res.stderr[-2000:]}")
        rec = json.loads(res.stdout.strip().splitlines()[-1])
        log(f"  stream devices={n}: {rec['gibps']} GiB/s "
            f"({rec['chunks']} chunks)")
        out["devices"].append(n)
        out["gibps"].append(rec["gibps"])
        out["staging_gibps"].append(rec["staging_gibps"])
        if check:
            out["identical"] = rec.get("identical", False)
            out["reconstruction_ok"] = rec.get("reconstruction_ok", False)
            out["chunks"] = rec.get("chunks")
    out["scale_max_devices"] = round(out["gibps"][-1] / out["gibps"][0], 3)
    return out


# ------------------------------------------------------------------ #
# phase 2 — the full node ingest stack (upload_stream -> download)
# ------------------------------------------------------------------ #

async def _node_phase(root: Path, p: dict) -> dict:
    from dfs_tpu.config import (ClusterConfig, FragmenterConfig,
                                NodeConfig, PeerAddr)
    from dfs_tpu.fragmenter.cdc_anchored_sharded import \
        ShardedAnchoredCdcFragmenter
    from dfs_tpu.node.runtime import StorageNodeServer
    from dfs_tpu.utils.hashing import sha256_hex

    ports = _free_ports(6)
    cluster = ClusterConfig(
        peers=tuple(PeerAddr(node_id=i + 1, host="127.0.0.1",
                             port=ports[2 * i],
                             internal_port=ports[2 * i + 1])
                    for i in range(3)),
        replication_factor=2)
    nodes = {}
    for i in (1, 2, 3):
        # tiny mode: the CONFIG carries the default region (the node's
        # production-derived geometry rejects a 16 KiB region, and the
        # lazy steps never build before the fragmenter swap below); the
        # tiny region rides the injected small-geometry fragmenter
        cfg = NodeConfig(
            node_id=i, cluster=cluster, data_root=root,
            fragmenter="cdc-anchored",
            frag=FragmenterConfig(
                devices=p["node_devices"],
                region_bytes=p["node_region"]
                if p["geometry"] == "full" else 0),
            health_probe_s=0)
        nodes[i] = StorageNodeServer(cfg)
        await nodes[i].start()
    # the config -> factory path must really select the sharded walk
    assert isinstance(nodes[1].fragmenter, ShardedAnchoredCdcFragmenter)
    if p["geometry"] == "tiny":
        # tiny smoke: production strips (the only geometry NodeConfig.cdc
        # can express) would compile for tens of seconds; swap in the
        # small-geometry sharded walk for the actual upload
        nodes[1].fragmenter = ShardedAnchoredCdcFragmenter(
            _params("tiny"),
            FragmenterConfig(devices=p["node_devices"],
                             region_bytes=p["node_region"]))
    try:
        rng = np.random.default_rng(31)
        data = rng.integers(0, 256, size=p["node_total"],
                            dtype=np.uint8).tobytes()

        async def body():
            for off in range(0, len(data), 1 << 20):
                yield data[off:off + (1 << 20)]

        t0 = time.perf_counter()
        manifest, _ = await nodes[1].upload_stream(body(), "shard.bin")
        dt = time.perf_counter() - t0
        frag = nodes[1].fragmenter
        _, got = await nodes[2].download(manifest.file_id)
        ident = (bytes(got) == data
                 and manifest.file_id == sha256_hex(data)
                 and not frag._unavailable)
        return {"devices": p["node_devices"],
                "region_bytes": p["node_region"],
                "bytes": len(data),
                "upload_seconds": round(dt, 4),
                "upload_gibps": round(len(data) / dt / 2**30, 4),
                "chunks": len(manifest.chunks),
                "byte_identical": bool(ident)}
    finally:
        for n in nodes.values():
            await n.stop()


# ------------------------------------------------------------------ #

def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tiny", action="store_true",
                    help="tier-1 smoke: machinery+identity gated, perf "
                         "reported but not gated")
    ap.add_argument("--out", default=None)
    ap.add_argument("--stream-worker", type=int, default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--region", type=int, default=8 * 2**20,
                    help=argparse.SUPPRESS)
    ap.add_argument("--total", type=int, default=96 * 2**20,
                    help=argparse.SUPPRESS)
    ap.add_argument("--repeats", type=int, default=3,
                    help=argparse.SUPPRESS)
    ap.add_argument("--geometry", default="full",
                    choices=["full", "tiny"], help=argparse.SUPPRESS)
    ap.add_argument("--check", action="store_true",
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.stream_worker is not None:
        return stream_worker(args.stream_worker, args.region, args.total,
                             args.repeats, args.geometry, args.check)
    p = TINY if args.tiny else FULL

    import tempfile

    out: dict = {"metric": "anchored_sharded_ingest", "round": 15,
                 "mode": "tiny" if args.tiny else "full"}
    log("phase 1: streamed anchored ingest scaling…")
    out["stream"] = stream_phase(p)
    log("phase 2: full-node upload_stream path…")
    base = "/dev/shm" if os.path.isdir("/dev/shm") \
        and os.access("/dev/shm", os.W_OK) else None
    with tempfile.TemporaryDirectory(prefix="bench_cdc_shard_",
                                     dir=base) as tmp:
        out["node"] = asyncio.run(_node_phase(Path(tmp), p))

    gates = (out["stream"].get("identical", False)
             and out["stream"].get("reconstruction_ok", False)
             and out["node"]["byte_identical"])
    if args.tiny:
        out["ok"] = bool(gates)
    else:
        out["ok"] = bool(gates
                         and out["stream"]["scale_max_devices"] >= 1.7)
    log(f"ok={out['ok']} stream={out['stream']['gibps']} "
        f"scale={out['stream']['scale_max_devices']} "
        f"node={out['node']['upload_gibps']} GiB/s")

    path = args.out or (None if args.tiny
                        else Path(__file__).parent / ART)
    if path:
        Path(path).write_text(json.dumps(out, indent=2) + "\n")
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
