"""Anchored two-level CDC fragmenters (v3) — shift-resilient + TPU-fast.

Strategy (ops.cdc_anchored): byte-granular content anchors choose segment
boundaries; within each segment the aligned 64-byte chunk grid re-anchors
at the segment start, so unaligned insertions only disturb their own
segment (the aligned v2 grid loses all downstream dedup — see
fragmenter/cdc_aligned.py). Chunking is identical whether the stream is
chunked whole, in any batching, or streamed: regions hand the device a
tile-aligned window with 8 bytes of lookback, and the unfinished tail
segment carries into the next region (ops.cdc_anchored.region_chunks).

The TPU walk is **pipelined**: windows advance by a fixed tile-aligned
stride (region_bytes - seg_max — always far enough that the carry lands
inside the next window), so every window's bytes are known upfront and
window k+1 can be device_put while window k computes; the carry position
chains as a DEVICE scalar (consumed_k - stride), so a multi-region stream
runs with zero host syncs until results are collected. This is the
host->HBM staging overlap the reference's synchronous upload loop
(StorageNode.java:118-189) has no analogue of.

- ``AnchoredCpuFragmenter`` — NumPy oracle path (chunk_file_anchored_np).
- ``AnchoredTpuFragmenter`` — full device pipeline, bounded-memory
  streaming in ~regions of ``region_bytes``.
"""

from __future__ import annotations

import numpy as np

from dfs_tpu.fragmenter.base import Fragmenter
from dfs_tpu.meta.manifest import ChunkRef, Manifest
from dfs_tpu.ops.cdc_anchored import (TILE_BYTES, AnchoredCdcParams,
                                      CutCapacityOverflow,
                                      chunk_file_anchored_np, region_buffer,
                                      region_chunks, region_collect,
                                      region_dispatch)
from dfs_tpu.ops.cdc_v2 import file_id_from_digests

_REGION_BYTES = 64 * 1024 * 1024
_CPU_CUTOFF = 2 * 1024 * 1024


def _to_u8(data) -> np.ndarray:
    if isinstance(data, np.ndarray):
        return data
    return np.frombuffer(data, dtype=np.uint8)


class _AnchoredBase(Fragmenter):
    def __init__(self, params: AnchoredCdcParams | None = None) -> None:
        self.params = params or AnchoredCdcParams()

    def manifest(self, data: bytes, name: str,
                 file_id: str | None = None) -> Manifest:
        chunks = tuple(self.chunk(data))
        return Manifest(
            file_id=file_id or file_id_from_digests(
                [c.digest for c in chunks]),
            name=name, size=len(data), fragmenter=self.name, chunks=chunks)


class AnchoredCpuFragmenter(_AnchoredBase):
    """Production CPU path: the C++ core (native/cdc_core.cpp —
    dfs_anchored_spans + batched SHA) when the toolchain is available,
    the NumPy oracle otherwise. Both are bit-identical to
    chunk_file_anchored_np, which tests enforce."""

    name = "cdc-anchored"

    def chunk(self, data: bytes) -> list[ChunkRef]:
        import hashlib

        from dfs_tpu.native import native_anchored_spans

        arr = _to_u8(data)
        spans = native_anchored_spans(arr, self.params)
        if spans is not None:
            # digests via hashlib over zero-copy memoryview slices:
            # OpenSSL's SHA-NI path measured 5x the portable C++ batch
            mv = memoryview(np.ascontiguousarray(arr))
            return [ChunkRef(index=i, offset=int(o), length=int(ln),
                             digest=hashlib.sha256(
                                 mv[o:o + ln]).hexdigest())
                    for i, (o, ln) in enumerate(spans)]
        out = chunk_file_anchored_np(arr, self.params)
        return [ChunkRef(index=i, offset=o, length=ln, digest=dg)
                for i, (o, ln, dg) in enumerate(out)]


class AnchoredTpuFragmenter(_AnchoredBase):
    """Device pipeline, region-batched; output is batching-independent."""

    name = "cdc-anchored-tpu"

    def __init__(self, params: AnchoredCdcParams | None = None,
                 region_bytes: int = _REGION_BYTES,
                 cpu_cutoff: int = _CPU_CUTOFF,
                 lane_multiple: int = 128,
                 max_inflight: int = 2) -> None:
        super().__init__(params)
        region_bytes = (int(region_bytes) // TILE_BYTES) * TILE_BYTES
        if region_bytes < 2 * self.params.seg_max:
            raise ValueError("region must hold at least two segments")
        self.region_bytes = region_bytes
        # fixed window stride: far enough that the previous window's carry
        # (>= window_end - seg_max) always lands inside the next window
        self.stride = region_bytes - self.params.seg_max
        self.cpu_cutoff = int(cpu_cutoff)
        self.lane_multiple = int(lane_multiple)
        self.max_inflight = max(1, int(max_inflight))

    # -- pipelined region walk shared by chunk() and manifest_stream() ----

    def _dispatch_window(self, fetch, base: int, n: int, start0,
                         final: bool) -> tuple:
        """device_put window [base, min(n, base+region_bytes)) and dispatch
        the fused chain; returns (base, end, final, out) with out all
        device arrays. ``fetch(off, ln)`` must return stream bytes as a u8
        array for any span inside [base-8, end). ``final`` must be passed
        explicitly — inferring it from end == n would misfire mid-stream
        when the bytes received so far happen to land exactly on a window
        end. Buffer shapes bucket to the next power of two (region_buffer),
        so a multi-window walk compiles once for the full windows plus at
        most once for the shorter tail window."""
        import jax

        end = min(n, base + self.region_bytes)
        lookback = np.zeros((8,), np.uint8)
        take = min(8, base)
        if take:
            lookback[8 - take:] = fetch(base - take, take)
        words = jax.device_put(region_buffer(
            fetch(base, end - base), lookback, self.params))
        out = region_dispatch(words, end - base, start0, final,
                              self.params, lane_multiple=self.lane_multiple)
        return base, end, final, out

    def _collect_window(self, base: int, end: int, final: bool, out, fetch,
                        chunks: list[ChunkRef], store) -> int:
        """Pull one window's results, append absolute-offset ChunkRefs;
        returns the absolute consumed bound. Verifies span contiguity (the
        device-chained carry has no per-region host check)."""
        expect = chunks[-1].offset + chunks[-1].length if chunks else 0
        try:
            spans, consumed = region_collect(out)
        except CutCapacityOverflow:
            # this window's content out-chunked the tight cut capacity —
            # redo it alone at the worst-case bound. The device carry
            # (consumed) that later windows chained on is capacity-
            # independent, so the rest of the pipeline stays valid.
            lookback = np.zeros((8,), np.uint8)
            take = min(8, base)
            if take:
                lookback[8 - take:] = fetch(base - take, take)
            spans, consumed = region_chunks(
                fetch(base, end - base), lookback, expect - base, final,
                self.params, lane_multiple=self.lane_multiple,
                cap_mode="full")
        for o, ln, dg in spans:
            off = base + o
            if off != expect:
                raise AssertionError(
                    f"anchored walk discontinuity at {off} (want {expect})")
            expect = off + ln
            c = ChunkRef(index=len(chunks), offset=off, length=ln, digest=dg)
            chunks.append(c)
            if store is not None:
                store(dg, fetch(off, ln).tobytes())
        return base + consumed

    def _walk(self, arr: np.ndarray, store=None) -> list[ChunkRef]:
        n = int(arr.shape[0])
        if n == 0:
            return []
        if n <= self.cpu_cutoff:
            spans = chunk_file_anchored_np(arr, self.params)
            out = [ChunkRef(index=i, offset=o, length=ln, digest=dg)
                   for i, (o, ln, dg) in enumerate(spans)]
            if store is not None:
                for c in out:
                    store(c.digest,
                          arr[c.offset:c.offset + c.length].tobytes())
            return out

        fetch = lambda off, ln: arr[off:off + ln]       # noqa: E731
        chunks: list[ChunkRef] = []
        pending: list[tuple] = []      # [(base, device outputs)]
        start0 = 0                     # int for window 0, device scalar after
        base = 0
        while True:
            if len(pending) >= self.max_inflight:   # cap live windows
                self._collect_window(*pending.pop(0), fetch, chunks, store)
            final = base + self.region_bytes >= n
            win = self._dispatch_window(fetch, base, n, start0, final)
            pending.append(win)
            if final:
                break
            start0 = win[3][0] - self.stride   # device-resident carry
            base += self.stride
        bound = 0
        for win in pending:
            bound = self._collect_window(*win, fetch, chunks, store)
        if bound != n:
            raise AssertionError(f"anchored walk ended at {bound} != {n}")
        return chunks

    def chunk(self, data: bytes) -> list[ChunkRef]:
        return self._walk(_to_u8(data))

    def manifest_stream(self, blocks, name: str, store=None) -> Manifest:
        """Bounded-memory PIPELINED streaming: same fixed-stride window
        schedule and device-chained carry as chunk() (the two paths emit
        identical chunks by construction), dispatching each full window as
        soon as its bytes arrive while up to ``max_inflight`` windows
        compute. The host buffer is trimmed to the oldest un-collected
        window's base minus the 8-byte lookback, so peak memory is
        ~(max_inflight + 1) windows regardless of stream length."""
        chunks: list[ChunkRef] = []
        buf = bytearray()
        buf_base = 0                   # absolute offset of buf[0]
        total = 0                      # absolute bytes received
        pending: list[tuple] = []
        start0 = 0
        base = 0
        done = False

        def fetch(off: int, ln: int) -> np.ndarray:
            if off < buf_base:
                raise AssertionError(
                    f"stream buffer trimmed past {off} (base {buf_base})")
            return np.frombuffer(buf, np.uint8,
                                 count=ln, offset=off - buf_base)

        def trim() -> None:
            nonlocal buf, buf_base
            oldest = pending[0][0] if pending else base
            keep_from = max(buf_base, oldest - 8)
            if keep_from > buf_base:
                del buf[:keep_from - buf_base]
                buf_base = keep_from

        def advance(n_known: int, final_ok: bool) -> None:
            """Dispatch every window whose bytes are fully buffered."""
            nonlocal base, start0, done
            while not done:
                full = base + self.region_bytes <= n_known
                final = final_ok and base + self.region_bytes >= n_known
                if not (full or final):
                    return
                if len(pending) >= self.max_inflight:
                    self._collect_window(*pending.pop(0), fetch, chunks,
                                         store)
                win = self._dispatch_window(fetch, base, n_known, start0,
                                            final)
                pending.append(win)
                trim()
                if final:
                    done = True
                    return
                start0 = win[3][0] - self.stride
                base += self.stride

        for blk in blocks:
            buf += blk
            total += len(blk)
            advance(total, final_ok=False)
        if total == 0:
            return Manifest(file_id=file_id_from_digests([]), name=name,
                            size=0, fragmenter=self.name, chunks=())
        if total <= self.cpu_cutoff and not pending and base == 0:
            # small streams take chunk()'s oracle fast path (identical
            # output either way; this skips device dispatch entirely)
            cl = self._walk(np.frombuffer(buf, np.uint8), store=store)
            return Manifest(
                file_id=file_id_from_digests([c.digest for c in cl]),
                name=name, size=total, fragmenter=self.name,
                chunks=tuple(cl))
        advance(total, final_ok=True)
        bound = 0
        while pending:
            bound = self._collect_window(*pending.pop(0), fetch, chunks,
                                         store)
            trim()
        if bound != total:
            raise AssertionError(
                f"anchored stream ended at {bound} != {total}")
        return Manifest(
            file_id=file_id_from_digests([c.digest for c in chunks]),
            name=name, size=total, fragmenter=self.name,
            chunks=tuple(chunks))
